"""Handoff interleaving explorer (DSTPU320) — the third lifecycle layer.

The static DSTPU3xx rules prove the router's code obeys the lifecycle
specs at every SITE; the shadow sanitizer proves one EXECUTION obeyed
them.  Neither proves the protocol is safe under every ORDERING of the
control-plane events that can race in production: heartbeat aging, a
straggler-drain verdict, a crash, a late answer from the corpse, a
journaled finish the router never observed.  This module closes that
gap the model-checking way: enumerate **every permutation** of a
bounded event set, drive the real :class:`ReplicaRouter` (no mocks of
the code under test — only the replicas are scripted) through each
ordering with a deterministic step clock, settle, and assert the
zero-loss/exactly-once contracts that ``docs/serving.md`` promises:

- **zero lost uids** — every submitted uid reaches a terminal outcome;
- **exactly-once finalize** — the set-once result table is respected at
  the MECHANISM level (an audited ``_finalize`` counts calls per uid;
  the table alone cannot distinguish "finalized once" from "finalized
  twice with the same value");
- **token determinism** — whichever replica serves a uid, by recompute,
  late answer, or journal adoption, the tokens are identical (the
  sampling-stream contract);
- **pop-once** — each result pops exactly once, a second pop raises;
- **drained bookkeeping** — no replica keeps phantom ``assigned`` uids
  and the router queue is empty once everything resolved.

Events are CONDITIONAL where the real controller's are: the scripted
drain verdict only fires on a HEALTHY replica, because
``_check_fleet_verdicts`` never drains a suspect or dead one — the
explorer must enumerate reachable interleavings, not inject
FSM-illegal transitions and blame the router.

Scale: the default :func:`crash_handoff_scenario` has 6 events → 720
orderings, a deliberate tier-1 size (a few seconds of scripted pumps,
no model, no device).  ``extended=True`` adds a replica freeze →
5040 orderings for the ``slow``-marked sweep.  Entry points:
:func:`explore` (library), ``python -m deepspeed_tpu.analysis
--audit-step serving-lifecycle`` (CLI, with the sanitizer jaxpr-parity
proof).
"""

import itertools
import json
import math
import os
import shutil
import tempfile

import numpy as np

from ..checkpoint import atomic
from ..inference import journal as jr
from ..inference import transfer as xfer
from ..inference.router import (ReplicaRouter, ReplicaHandle, RouterConfig,
                                HEALTHY, DRAINING)
from ..inference.serving import Request, OK, stream_snapshot_dir
from ..utils.retry import RetryPolicy
from .findings import Finding

INTERLEAVE_VIOLATION = "DSTPU320"
PREFIX_INTERLEAVE_VIOLATION = "DSTPU321"   # prefix-sharing refcount races


class StepClock:
    """Deterministic manual clock — time moves only when an event or
    the settle loop advances it, so every permutation replays
    exactly."""

    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


class ScriptedReplica(ReplicaHandle):
    """A replica the explorer fully controls: heartbeat follows the
    step clock unless frozen, death is a flag, answers are injected —
    and an optional REAL on-disk journal lets a permutation exercise
    ``journal.replay`` adoption, not a stub of it."""

    def __init__(self, name, clock, journal_root=None, ledger=None):
        self.name = name
        self._clock = clock
        self.hb = clock()
        self.inbox = []
        self.frozen = False
        self.exited = False
        self._answers = []
        self._jdir = None
        self._journal = None
        # KV-migration script state: router-handed snapshot hints,
        # a crash-mid-restore flag, and the shared emission ledger the
        # no-stale-tokens oracle reads
        self.restore_hints = {}
        self.restore_broken = False
        self._ledger = ledger
        if journal_root is not None:
            self._jdir = os.path.join(journal_root, name)
            os.makedirs(self._jdir, exist_ok=True)

    # ------------------------------------------------ handle interface
    def submit(self, req, snapshot_dir=None, seat=None):
        self.inbox.append(req)
        if snapshot_dir is not None:
            # resolve the restore EAGERLY, like submit_restored: seat
            # the image at admission or fall back to recompute on the
            # spot.  restore_broken models a crash mid-import — the
            # stream silently degrades to the plain recompute path
            if self.restore_broken:
                return
            # the scripted replica TRUSTS the router-handed image —
            # the oracles must catch a bad handoff, the replica must
            # not mask it
            with open(os.path.join(snapshot_dir, "stream.json")) as f:
                self.restore_hints[int(req.uid)] = json.load(f)

    def pump(self):
        if not self.frozen and not self.exited:
            self.hb = self._clock()

    def poll(self):
        out, self._answers = self._answers, []
        return out

    def heartbeat(self):
        return self.hb

    def alive(self):
        return not self.exited

    @property
    def journal_dir(self):
        return self._jdir

    # ------------------------------------------------ script controls
    def answer(self, uid, tokens, outcome=OK):
        """Inject a finished result (legal even frozen/dead — a hung
        replica answering LATE is exactly the dedup case)."""
        self._answers.append({"uid": int(uid), "outcome": outcome,
                              "tokens": list(tokens)})

    def serve(self, token_fn):
        """Answer everything in the inbox (a healthy replica doing its
        job); no-op while frozen or dead.  A stream seated from a
        restore at :meth:`submit` resumes from the snapshot's position,
        emitting ONLY the post-snapshot suffix; everything else is a
        full recompute.  Emissions land on the shared ledger for the
        no-stale-tokens oracle."""
        if self.frozen or self.exited:
            return
        for req in self.inbox:
            uid = int(req.uid)
            snap = self.restore_hints.pop(uid, None)
            full = token_fn(uid)
            if snap is not None:
                pos = int(snap["pos"])
                full = list(snap["prefix"]) + full[pos:]
                emitted, via = range(pos, len(full)), "restore"
            else:
                emitted, via = range(len(full)), "recompute"
            if self._ledger is not None:
                for i in emitted:
                    self._ledger.append({"replica": self.name, "uid": uid,
                                         "index": i, "via": via})
            self.answer(uid, full)
        self.inbox = []

    def journal_finish(self, uid, tokens, outcome=OK):
        """Durably journal a finish the router has NOT observed — the
        crash-handoff adoption case (answered, journaled, died before
        the router's next poll)."""
        assert self._jdir is not None, f"replica {self.name} has no journal"
        if self._journal is None:
            self._journal = jr.RequestJournal(self._jdir, clock=self._clock)
        self._journal.finish(int(uid), outcome, list(tokens))

    def journal_transfer(self, uid, entry, gen, seat):
        """Durably journal a publish exactly like a prefill worker's
        ``_publish_slot``: the eager ``transfer`` record first, then the
        ``transferred`` finish that retires the slot — so a recovering
        router sees the handoff, never a pending uid with lost work."""
        assert self._jdir is not None, f"replica {self.name} has no journal"
        if self._journal is None:
            self._journal = jr.RequestJournal(self._jdir, clock=self._clock)
        self._journal.transfer(int(uid), entry, gen, 0, 0.0, seat=seat)
        self._journal.finish(int(uid), xfer.TRANSFERRED, None)
        self._journal.flush()


class _AuditedRouter(ReplicaRouter):
    """The real router plus a finalize call-counter per uid — the
    exactly-once oracle the end-state table cannot provide."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.finalize_counts = {}

    def _finalize(self, rec, outcome, tokens, why):
        uid = int(rec["uid"])
        self.finalize_counts[uid] = self.finalize_counts.get(uid, 0) + 1
        super()._finalize(rec, outcome, tokens, why)


# ------------------------------------------------------------- scenario
def _token_fn(uid):
    # pure function of the request — the determinism contract in
    # miniature (docs/serving.md: fold_in(PRNGKey(seed), index))
    return [int(uid) * 10 + 1, int(uid) * 10 + 2]


def crash_handoff_scenario(extended=False):
    """The default bounded event set: replica ``a`` takes traffic,
    ages, may be drained by a verdict, crashes with work in flight,
    journals a finish the router never saw, and answers late from the
    grave; replica ``b`` survives and absorbs the handoff.  6 events
    (720 orderings); ``extended`` adds a freeze (hang) → 7 events
    (5040)."""

    def build(workdir):
        clock = StepClock(1000.0)
        a = ScriptedReplica("a", clock, journal_root=workdir)
        b = ScriptedReplica("b", clock)
        cfg = RouterConfig(
            suspect_after_s=1.0, dead_after_s=4.0,
            probe_retry=RetryPolicy(max_attempts=3, base_delay_s=0.2,
                                    max_delay_s=0.2, jitter_mode="full",
                                    seed=7, sleep=lambda s: None),
            monitor_interval=1)
        router = _AuditedRouter([a, b], cfg, clock=clock)
        uids = [router.submit(Request(tokens=np.arange(4) % 64,
                                      max_new_tokens=2, seed=i))
                for i in range(3)]
        router.pump()                       # deterministic placement
        a_uids = sorted(router._replicas["a"].assigned)
        assert a_uids, "scenario assumes replica a took traffic"
        return {"router": router, "clock": clock, "a": a, "b": b,
                "uids": uids, "a_uids": a_uids, "token_fn": _token_fn}

    def ev_pump(w):
        w["router"].pump()

    def ev_age(w):
        # heartbeats go stale (no replica pump until the next router
        # pump) — the suspect/probe path
        w["clock"].advance(1.5)

    def ev_drain_a(w):
        # the straggler/SLO verdict — fires only on HEALTHY, exactly
        # like _check_fleet_verdicts (conditional event, see module
        # docstring)
        st = w["router"]._replicas["a"]
        if st.state == HEALTHY:
            w["router"]._set_state(st, DRAINING, w["clock"](),
                                   "scripted straggler verdict")

    def ev_crash_a(w):
        w["a"].exited = True

    def ev_journal_finish_a(w):
        uid = w["a_uids"][0]
        w["a"].journal_finish(uid, w["token_fn"](uid))

    def ev_late_answer_a(w):
        uid = w["a_uids"][-1]
        w["a"].answer(uid, w["token_fn"](uid))

    def ev_freeze_a(w):
        w["a"].frozen = True

    events = [("pump", ev_pump),
              ("age-heartbeats", ev_age),
              ("drain-a", ev_drain_a),
              ("crash-a", ev_crash_a),
              ("journal-finish-a", ev_journal_finish_a),
              ("late-answer-a", ev_late_answer_a)]
    if extended:
        events.append(("freeze-a", ev_freeze_a))
    return {"name": "crash-handoff" + ("-extended" if extended else ""),
            "build": build, "events": events}


def migration_scenario():
    """The KV-migration event alphabet (docs/serving.md#kv-migration):
    replica ``a`` commits a cadence snapshot of one stream, a SECOND
    snapshot is torn mid-write (staged, never committed, content
    poisoned so an erroneous restore fails the token-identity oracle),
    ``a`` crashes, the survivor's restore may itself die mid-import
    (falling back to recompute), and a journaled finish races all of
    it.  6 events → 720 orderings.  On top of the base contracts the
    sweep asserts the **no-stale-tokens oracle**: a restored stream
    never re-emits a token index the original already reported durably
    (i.e. restore emission starts at the snapshot position), and a
    torn image is never restored at all."""

    def build(workdir):
        clock = StepClock(1000.0)
        ledger = []
        a = ScriptedReplica("a", clock, journal_root=workdir,
                            ledger=ledger)
        b = ScriptedReplica("b", clock, ledger=ledger)
        cfg = RouterConfig(
            suspect_after_s=1.0, dead_after_s=4.0,
            probe_retry=RetryPolicy(max_attempts=3, base_delay_s=0.2,
                                    max_delay_s=0.2, jitter_mode="full",
                                    seed=7, sleep=lambda s: None),
            monitor_interval=1)
        router = _AuditedRouter([a, b], cfg, clock=clock)
        uids = [router.submit(Request(tokens=np.arange(4) % 64,
                                      max_new_tokens=2, seed=i))
                for i in range(3)]
        router.pump()                       # deterministic placement
        a_uids = sorted(router._replicas["a"].assigned)
        assert a_uids, "scenario assumes replica a took traffic"
        return {"router": router, "clock": clock, "a": a, "b": b,
                "uids": uids, "a_uids": a_uids, "token_fn": _token_fn,
                "ledger": ledger, "snap_pos": {}}

    def ev_pump(w):
        w["router"].pump()

    def ev_snapshot_a(w):
        # the cadence snapshot: only a LIVE replica exports (the
        # engine's step loop died with the process), committed through
        # the real stage/manifest/rename protocol so find_latest_valid
        # accepts it
        if w["a"].exited:
            return
        uid = w["a_uids"][0]
        pos = 1
        sdir = stream_snapshot_dir(w["a"].journal_dir, uid)
        stage = atomic.stage_path(sdir, "snap-000001")
        os.makedirs(stage, exist_ok=True)
        with open(os.path.join(stage, "stream.json"), "w") as f:
            json.dump({"uid": uid, "pos": pos,
                       "prefix": w["token_fn"](uid)[:pos]}, f)
        atomic.write_manifest(stage, meta={"global_steps": pos})
        atomic.commit_staged(sdir, "snap-000001")
        w["snap_pos"][uid] = pos

    def ev_torn_snapshot_a(w):
        # crash mid-snapshot: a NEWER image staged but never committed
        # (no manifest, no rename).  Its content is poisoned — if any
        # path ever restores it, the token-identity oracle screams
        uid = w["a_uids"][0]
        sdir = stream_snapshot_dir(w["a"].journal_dir, uid)
        stage = atomic.stage_path(sdir, "snap-000002")
        os.makedirs(stage, exist_ok=True)
        with open(os.path.join(stage, "stream.json"), "w") as f:
            json.dump({"uid": uid, "pos": 1, "prefix": [999]}, f)

    def ev_crash_a(w):
        w["a"].exited = True

    def ev_break_restore_b(w):
        # crash mid-restore at the survivor: the import dies and the
        # stream falls back to a full recompute (submit_restored's
        # fallback contract) — never a lost or duplicated uid
        w["b"].restore_broken = True

    def ev_journal_finish_a(w):
        uid = w["a_uids"][-1]
        w["a"].journal_finish(uid, w["token_fn"](uid))

    events = [("pump", ev_pump),
              ("snapshot-a", ev_snapshot_a),
              ("torn-snapshot-a", ev_torn_snapshot_a),
              ("crash-a", ev_crash_a),
              ("break-restore-b", ev_break_restore_b),
              ("journal-finish-a", ev_journal_finish_a)]
    return {"name": "kv-migration", "build": build, "events": events}


def disagg_handoff_scenario():
    """The prefill→decode handoff event alphabet
    (docs/serving.md#disaggregation): replica ``a`` plays the prefill
    worker for one of its streams — it commits a transfer entry through
    the real stage/manifest/rename protocol, journals the ``transfer``
    record + ``transferred`` finish (the durability order
    ``_publish_slot`` guarantees), and retires the stream from its own
    inbox.  The handoff can then reach the router two racing ways: the
    poll-surface ``kind=transfer`` record (possibly LATE, from the
    grave), or the crash path — ``a`` dies and ``_handoff`` must seat
    the uid from ``find_transfer_entry`` instead of adopting the
    prefill side's partial state.  A SECOND, poisoned entry is staged
    but never committed (torn publish), and a journaled finish of an
    unrelated uid races everything.  6 events → 720 orderings.

    On top of the base contracts the migration oracles carry over: the
    no-stale-tokens ledger proves the decode side resumes AT the seat
    position (never re-emitting the prefill worker's tokens), and the
    torn entry is never seated at all."""

    def build(workdir):
        clock = StepClock(1000.0)
        ledger = []
        a = ScriptedReplica("a", clock, journal_root=workdir,
                            ledger=ledger)
        b = ScriptedReplica("b", clock, ledger=ledger)
        cfg = RouterConfig(
            suspect_after_s=1.0, dead_after_s=4.0,
            probe_retry=RetryPolicy(max_attempts=3, base_delay_s=0.2,
                                    max_delay_s=0.2, jitter_mode="full",
                                    seed=7, sleep=lambda s: None),
            monitor_interval=1)
        router = _AuditedRouter([a, b], cfg, clock=clock)
        uids = [router.submit(Request(tokens=np.arange(4) % 64,
                                      max_new_tokens=2, seed=i))
                for i in range(3)]
        router.pump()                       # deterministic placement
        a_uids = sorted(router._replicas["a"].assigned)
        assert len(a_uids) >= 2, \
            "scenario assumes replica a took the transfer AND the " \
            "journaled-finish stream"
        return {"router": router, "clock": clock, "a": a, "b": b,
                "uids": uids, "a_uids": a_uids, "token_fn": _token_fn,
                "ledger": ledger, "snap_pos": {},
                "xfer_entry": None, "announced": False}

    def ev_pump(w):
        w["router"].pump()

    def _announce(w):
        # the poll surface of a publish: the SAME record LocalReplica
        # /ProcessReplica.poll translate a transferred outcome into
        uid = w["a_uids"][0]
        w["a"]._answers.append({"kind": "transfer", "uid": uid,
                                "entry": w["xfer_entry"],
                                "seat": w["xfer_seat"], "gen": 1,
                                "bytes": 0})
        w["announced"] = True

    def ev_publish_a(w):
        # the prefill worker finishes prefill + first token and commits
        # the handoff: entry on disk (atomic), journal records durable,
        # stream retired from the local inbox — only a LIVE replica
        # publishes (the engine died with the process otherwise)
        if w["a"].exited or w["xfer_entry"] is not None:
            return
        uid = w["a_uids"][0]
        pos = 1
        qdir = xfer.transfer_dir(w["a"].journal_dir)
        tag = f"xfer-{uid:08d}-{pos:06d}"
        stage = atomic.stage_path(qdir, tag)
        os.makedirs(stage, exist_ok=True)
        with open(os.path.join(stage, "stream.json"), "w") as f:
            json.dump({"uid": uid, "pos": pos,
                       "prefix": w["token_fn"](uid)[:pos]}, f)
        seat = {"uid": uid, "gen": pos,
                "first_token": w["token_fn"](uid)[0]}
        atomic.write_manifest(stage, meta={"global_steps": pos,
                                           "kind": "kv_transfer",
                                           "seat": seat})
        atomic.commit_staged(qdir, tag)
        w["xfer_entry"] = os.path.join(qdir, tag)
        w["xfer_seat"] = seat
        w["snap_pos"][uid] = pos
        w["a"].journal_transfer(uid, w["xfer_entry"], pos, seat)
        w["a"].inbox = [r for r in w["a"].inbox if int(r.uid) != uid]

    def ev_torn_publish_a(w):
        # crash mid-publish: staged, no manifest, no rename — invisible
        # to find_valid_tags/find_transfer_entry.  Poisoned content: if
        # any path ever seats it, the token-identity oracle screams
        uid = w["a_uids"][0]
        qdir = xfer.transfer_dir(w["a"].journal_dir)
        stage = atomic.stage_path(qdir, f"xfer-{uid:08d}-{2:06d}")
        os.makedirs(stage, exist_ok=True)
        with open(os.path.join(stage, "stream.json"), "w") as f:
            json.dump({"uid": uid, "pos": 1, "prefix": [999]}, f)

    def ev_announce_transfer_a(w):
        # the publish reaches the router via poll — legal even frozen or
        # dead (a late answer from the corpse is exactly the set-once
        # dedup case); meaningless before the publish exists
        if w["xfer_entry"] is None or w["announced"]:
            return
        _announce(w)

    def ev_crash_a(w):
        w["a"].exited = True

    def ev_journal_finish_a(w):
        uid = w["a_uids"][-1]
        w["a"].journal_finish(uid, w["token_fn"](uid))

    def settle(w):
        # a committed publish ALWAYS reaches the router eventually: by
        # the poll surface (inject it now if the ordering skipped it) or
        # by _handoff's find_transfer_entry after the crash — both in
        # the same settle, the second arrival must dedup
        if w["xfer_entry"] is not None and not w["announced"]:
            _announce(w)
        _settle(w)

    events = [("pump", ev_pump),
              ("publish-a", ev_publish_a),
              ("torn-publish-a", ev_torn_publish_a),
              ("announce-transfer-a", ev_announce_transfer_a),
              ("crash-a", ev_crash_a),
              ("journal-finish-a", ev_journal_finish_a)]
    return {"name": "disagg-handoff", "build": build, "events": events,
            "settle": settle}


def prefix_sharing_scenario():
    """Prefix-sharing refcount protocol explorer (``DSTPU321``).

    The radix cache (docs/serving.md#prefix-sharing) adds a third class
    of holder to every KV block — the cache's own reference, beside the
    owning stream and any co-tenant readers — and its events race in
    production exactly like the router's: a publish (at seat or at
    finish), a co-tenant attach taking shares, a finish decref'ing, an
    eviction pass under pool pressure, a cache clear at close.  This
    scenario drives the REAL :class:`~..inference.paged_kv.BlockAllocator`
    + :class:`~..inference.paged_kv.PrefixIndex` (no model, no router)
    through every ordering of that alphabet — 6 events, 720 orderings —
    and asserts the refcount contracts:

    - **no torn refcount** — no ordering raises a double free, an
      incref-of-free, or a free-of-scratch from legal protocol calls;
    - **no reclaim-under-reader** — eviction and clear never physically
      release a block a live tenant still holds;
    - **cache lists only live blocks** — the index never maps a key to
      a block whose refcount dropped to zero;
    - **pool conservation** — after settle (finish both tenants, clear
      the cache) every block is back on the free list and the logical
      refcount sum is zero.

    Tenant ``b``'s attach is CONDITIONAL on what the ordering already
    made visible: after publish it takes the shared prefix via
    ``match`` + ``incref``; before publish (or after a clear) it
    degrades to a fully private allocation — both legal, both checked.
    """
    from ..inference import paged_kv as pk

    BS = 4
    PROMPT_A = tuple(range(1, 13))                  # 3 full blocks
    PROMPT_B = PROMPT_A[:8] + (91, 92, 93, 94)      # shares 2, diverges

    def _live_held(w):
        held = set()
        for name in ("a", "b"):
            t = w[name]
            if not t["done"] and t["blocks"]:
                held.update(t["blocks"])
        return held

    def _inv(w, label):
        # invariants re-checked after EVERY event, so a violation names
        # the event that introduced it, not the settle that found it
        alloc, idx = w["alloc"], w["idx"]
        for name in ("a", "b"):
            t = w[name]
            if t["done"] or not t["blocks"]:
                continue
            for b in t["blocks"]:
                if not alloc.is_allocated(b):
                    w["violations"].append(
                        f"{label}: live tenant {name!r} block {b} was "
                        f"reclaimed out from under it")
        for b in list(idx._by_block):
            if alloc.refcount(b) < 1:
                w["violations"].append(
                    f"{label}: cache lists block {b} with refcount 0")

    def _publish(w, label):
        # index tenant a's full prompt blocks (publish-at-seat, or the
        # publish-at-finish the settle/finish path replays)
        t = w["a"]
        if t["published"] or t["done"]:
            return
        parent = None
        for i in range(len(t["prompt"]) // BS):
            chunk = t["prompt"][i * BS:(i + 1) * BS]
            try:
                key = w["idx"].insert(parent, chunk, t["blocks"][i])
            except ValueError as e:
                w["violations"].append(
                    f"{label}: unexpected refcount fault on insert: {e}")
                return
            if key is None:     # broken chain after a racing clear: legal
                break
            parent = key
        t["published"] = True
        _inv(w, label)

    def _attach_b(w, label):
        t = w["b"]
        if t["blocks"] is not None:
            return
        limit = (len(PROMPT_B) - 1) // BS       # the write-safety clamp
        m = w["idx"].match(PROMPT_B, BS, limit_blocks=limit)
        shared = list(m["blocks"])
        need = len(PROMPT_B) // BS - len(shared)
        try:
            w["alloc"].incref(shared)
        except ValueError as e:
            w["violations"].append(
                f"{label}: incref of matched prefix failed: {e}")
            return
        fresh = w["alloc"].alloc(need)
        if fresh is None:
            w["violations"].append(
                f"{label}: pool exhausted attaching tenant b "
                f"(free={w['alloc'].free_blocks}, need={need})")
            w["alloc"].free(shared)
            return
        t["blocks"] = shared + fresh
        t["shared"] = len(shared)
        _inv(w, label)

    def _finish(w, name, label):
        t = w[name]
        if t["done"] or t["blocks"] is None:
            return
        if name == "a":
            _publish(w, label)      # the engine publishes before freeing
        try:
            w["alloc"].free(t["blocks"])
        except ValueError as e:
            w["violations"].append(
                f"{label}: torn refcount freeing tenant {name!r}: {e}")
        t["done"] = True
        _inv(w, label)

    def build(workdir):
        alloc = pk.BlockAllocator(10)           # 9 allocatable
        idx = pk.PrefixIndex(alloc)
        w = {"alloc": alloc, "idx": idx, "violations": [],
             "a": {"prompt": PROMPT_A, "blocks": alloc.alloc(3),
                   "published": False, "done": False},
             "b": {"prompt": PROMPT_B, "blocks": None, "shared": 0,
                   "done": False}}
        return w

    def ev_publish_a(w):
        _publish(w, "publish-a")

    def ev_attach_b(w):
        _attach_b(w, "attach-b")

    def ev_finish_a(w):
        _finish(w, "a", "finish-a")

    def ev_finish_b(w):
        _finish(w, "b", "finish-b")

    def ev_evict(w):
        held = _live_held(w)
        released = w["idx"].evict(3)
        for b in released:
            if b in held:
                w["violations"].append(
                    f"evict-pressure: eviction released block {b} a "
                    f"live tenant still holds")
        _inv(w, "evict-pressure")

    def ev_clear(w):
        held = _live_held(w)
        try:
            _, released = w["idx"].clear()
        except ValueError as e:
            w["violations"].append(
                f"clear-cache: torn refcount clearing the index: {e}")
            return
        for b in released:
            if b in held:
                w["violations"].append(
                    f"clear-cache: clear released block {b} a live "
                    f"tenant still holds")
        _inv(w, "clear-cache")

    def settle(w):
        # finish whatever the ordering left live, then drop the cache
        if w["b"]["blocks"] is None:
            _attach_b(w, "settle")
        _finish(w, "a", "settle")
        _finish(w, "b", "settle")
        ev_clear(w)

    def check(w):
        viol = list(w["violations"])
        alloc, idx = w["alloc"], w["idx"]
        if alloc.used_blocks or alloc.free_blocks != alloc.num_blocks - 1:
            viol.append(
                f"pool not conserved after settle: used="
                f"{alloc.used_blocks} free={alloc.free_blocks} of "
                f"{alloc.num_blocks - 1}")
        if alloc.logical_blocks:
            viol.append(f"{alloc.logical_blocks} logical refcount(s) "
                        f"survive settle — a holder never let go")
        if len(idx):
            viol.append(f"{len(idx)} cache entr(ies) survive clear")
        return viol

    events = [("publish-a", ev_publish_a),
              ("attach-b", ev_attach_b),
              ("finish-a", ev_finish_a),
              ("finish-b", ev_finish_b),
              ("evict-pressure", ev_evict),
              ("clear-cache", ev_clear)]
    return {"name": "prefix-sharing", "build": build, "events": events,
            "settle": settle, "check": check,
            "rule": PREFIX_INTERLEAVE_VIOLATION}


# -------------------------------------------------------------- explore
def _settle(w, max_iters=64):
    """Post-scenario service: the surviving replicas answer their
    inboxes and the router pumps until nothing is outstanding (bounded
    — a protocol that CANNOT settle is itself a violation, reported by
    the lost-uid check)."""
    r = w["router"]
    for _ in range(max_iters):
        if not r._outstanding():
            return
        w["clock"].advance(1.0)
        for rep in (w["a"], w["b"]):
            rep.serve(w["token_fn"])
        r.pump()


def _check(w):
    """The contract checks; returns human-readable violation strings."""
    viol = []
    r = w["router"]
    for uid in w["uids"]:
        rec = r.results.get(uid)
        if rec is None:
            viol.append(f"uid {uid} vanished from the result table")
        elif rec["outcome"] is None:
            viol.append(f"uid {uid} lost — no terminal outcome after "
                        f"settle")
    for uid in w["uids"]:
        n = r.finalize_counts.get(uid, 0)
        if n != 1:
            viol.append(f"uid {uid} finalized {n} time(s) — set-once "
                        f"requires exactly 1")
    for uid in w["uids"]:
        rec = r.results.get(uid)
        if rec is None or rec["outcome"] is None:
            continue
        if rec["outcome"] != OK:
            viol.append(f"uid {uid} ended {rec['outcome']!r}, expected "
                        f"{OK!r} (no shed/deadline policy is armed)")
        elif list(rec["tokens"] or []) != w["token_fn"](uid):
            viol.append(f"uid {uid} tokens {rec['tokens']} != "
                        f"deterministic {w['token_fn'](uid)} — the "
                        f"re-run/late-answer/adoption paths disagreed")
    popped = 0
    for uid in w["uids"]:
        try:
            r.pop_result(uid)
            popped += 1
        except Exception as e:            # lost uids already reported
            viol.append(f"pop_result({uid}) failed: {type(e).__name__}")
    if popped:
        try:
            r.pop_result(w["uids"][0])
            viol.append(f"uid {w['uids'][0]} popped TWICE — the "
                        f"exactly-once serve contract is broken")
        except KeyError:
            pass
    for name, st in r._replicas.items():
        if st.assigned:
            viol.append(f"replica {name!r} still holds phantom assigned "
                        f"uids {sorted(st.assigned)}")
    if r.queue:
        viol.append(f"{len(r.queue)} request(s) stranded in the router "
                    f"queue")
    # no-stale-tokens oracle (migration scenarios only): a restored
    # stream resumes AT the committed snapshot position — indices the
    # original already reported durably are never re-emitted — and a
    # uid with no committed snapshot is never served via restore (a
    # torn image restored is exactly that)
    snap_pos = w.get("snap_pos") or {}
    for e in (w.get("ledger") or []):
        if e["via"] != "restore":
            continue
        pos = snap_pos.get(e["uid"])
        if pos is None:
            viol.append(f"uid {e['uid']} served via restore with no "
                        f"committed snapshot — a torn/corrupt image "
                        f"was restored")
        elif e["index"] < pos:
            viol.append(f"uid {e['uid']} re-emitted token index "
                        f"{e['index']} via restore; the original "
                        f"durably reported indices < {pos} "
                        f"(no-stale-tokens)")
    return viol


def explore(scenario=None, max_permutations=None, workdir=None):
    """Run ``scenario`` under every permutation of its event set.

    Returns a report dict; ``report["findings"]`` holds one
    :class:`Finding` (rule ``DSTPU320``, severity error) per violating
    ordering, carrying the exact event order in ``extra`` so a failure
    is a reproducer, not a shrug."""
    scenario = scenario or crash_handoff_scenario()
    labels = [lbl for lbl, _ in scenario["events"]]
    settle = scenario.get("settle", _settle)
    check = scenario.get("check", _check)
    rule = scenario.get("rule", INTERLEAVE_VIOLATION)
    own_tmp = workdir is None
    if own_tmp:
        workdir = tempfile.mkdtemp(prefix="dstpu-interleave-")
    explored, findings = 0, []
    try:
        for perm in itertools.permutations(scenario["events"]):
            if max_permutations is not None and \
                    explored >= max_permutations:
                break
            explored += 1
            order = [lbl for lbl, _ in perm]
            w = scenario["build"](
                os.path.join(workdir, f"perm-{explored:05d}"))
            for _, fn in perm:
                fn(w)
            settle(w)
            for v in check(w):
                findings.append(Finding(
                    rule, "error",
                    f"[{' -> '.join(order)}] {v}",
                    eqn_path=f"interleave/{scenario['name']}",
                    extra={"order": order, "scenario": scenario["name"]}))
    finally:
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)
    return {"scenario": scenario["name"], "events": labels,
            "total_permutations": math.factorial(len(labels)),
            "explored": explored, "violations": len(findings),
            "findings": findings, "ok": not findings}
