"""Machine-readable findings shared by the jaxpr auditor and the lint pass.

One schema for both: a lint finding anchors to ``file:line``, an auditor
finding anchors to an equation path inside the audited jaxpr
(``eqn_path`` like ``pjit/scan/dot_general[3]``).  Severity gates the CLI
exit code: ``error`` findings fail the run, ``warning``/``info`` report.
"""

from dataclasses import dataclass, field
from typing import Optional

SEVERITIES = ("info", "warning", "error")


@dataclass
class Finding:
    rule: str                      # stable rule id, e.g. "DSTPU102"
    severity: str                  # "info" | "warning" | "error"
    message: str
    file: Optional[str] = None     # repo-relative path (lint findings)
    line: Optional[int] = None
    eqn_path: Optional[str] = None  # jaxpr equation path (audit findings)
    extra: dict = field(default_factory=dict)

    def __post_init__(self):
        assert self.severity in SEVERITIES, self.severity

    @property
    def location(self) -> str:
        if self.file is not None:
            return f"{self.file}:{self.line}" if self.line else self.file
        return self.eqn_path or "<program>"

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "severity": self.severity,
             "message": self.message, "location": self.location}
        if self.file is not None:
            d["file"] = self.file
        if self.line is not None:
            d["line"] = self.line
        if self.eqn_path is not None:
            d["eqn_path"] = self.eqn_path
        if self.extra:
            d["extra"] = self.extra
        return d

    def __str__(self):
        return f"{self.location}: {self.severity}: {self.rule}: {self.message}"


def counts_by_severity(findings) -> dict:
    out = {s: 0 for s in SEVERITIES}
    for f in findings:
        out[f.severity] += 1
    return out


def worst_severity(findings) -> Optional[str]:
    worst = None
    for f in findings:
        if worst is None or SEVERITIES.index(f.severity) > SEVERITIES.index(worst):
            worst = f.severity
    return worst
