"""Static analysis for compiled-step performance invariants.

Two passes (docs/static-analysis.md):

  - :mod:`jaxpr_audit` — given a jitted callable + example args (or a
    ``DeepSpeedEngine`` via :func:`audit_engine`), statically verifies
    the properties the perf story depends on: no host callbacks in the
    step, no dtype promotion above the configured compute dtype,
    donation actually honored by the compiled executable, the per-step
    collective census within a declared comms budget, and no
    weak-typed-scalar recompile hazards.
  - :mod:`lint` — an AST rule engine (bare except, swallowed OSError,
    tracing-safety rules, and the DSTPU3xx lifecycle/typestate family
    over the serving control plane) with per-site suppression comments
    (stale suppressions are themselves findings, DSTPU003).
  - the **lifecycle verifier** (docs/static-analysis.md#lifecycle) —
    three layers over one set of FSM specs
    (``lint/lifecycle.py``): static typestate rules (DSTPU30x), the
    runtime :class:`~.sanitize.ShadowSanitizer` (DSTPU31x, armed via
    ``--sanitize``/``DSTPU_SANITIZE``/``analysis.sanitize``), and the
    :mod:`~.interleave` handoff permutation explorer (DSTPU320).
    ``interleave`` is imported as a submodule on purpose — it drives
    the router, which would make a top-level import circular.

CLI: ``python -m deepspeed_tpu.analysis [paths] [--rules ...] [--json]``.
"""

from .comms import COLLECTIVE_KINDS, CommsBudget, check_budget, summarize
from .findings import Finding, counts_by_severity, worst_severity
from .jaxpr_audit import AuditReport, audit_engine, audit_fn, iter_eqns
from .lint import REGISTRY, lint_file, lint_paths, select_rules
from .lint import rules as _rules  # noqa: F401  (populate REGISTRY)
from .lint import lifecycle as _lifecycle  # noqa: F401  (DSTPU3xx family)
from .sanitize import (SANITIZER_CODES, SanitizerError, ShadowSanitizer)

__all__ = [
    "AuditReport", "CommsBudget", "COLLECTIVE_KINDS", "Finding",
    "REGISTRY", "SANITIZER_CODES", "SanitizerError", "ShadowSanitizer",
    "audit_engine", "audit_fn", "check_budget",
    "counts_by_severity", "iter_eqns", "lint_file", "lint_paths",
    "select_rules", "summarize", "worst_severity",
]
