"""Static analysis for compiled-step performance invariants.

Two passes (docs/static-analysis.md):

  - :mod:`jaxpr_audit` — given a jitted callable + example args (or a
    ``DeepSpeedEngine`` via :func:`audit_engine`), statically verifies
    the properties the perf story depends on: no host callbacks in the
    step, no dtype promotion above the configured compute dtype,
    donation actually honored by the compiled executable, the per-step
    collective census within a declared comms budget, and no
    weak-typed-scalar recompile hazards.
  - :mod:`lint` — an AST rule engine (bare except, swallowed OSError,
    tracing-safety rules) with per-site suppression comments.

CLI: ``python -m deepspeed_tpu.analysis [paths] [--rules ...] [--json]``.
"""

from .comms import COLLECTIVE_KINDS, CommsBudget, check_budget, summarize
from .findings import Finding, counts_by_severity, worst_severity
from .jaxpr_audit import AuditReport, audit_engine, audit_fn, iter_eqns
from .lint import REGISTRY, lint_file, lint_paths, select_rules
from .lint import rules as _rules  # noqa: F401  (populate REGISTRY)

__all__ = [
    "AuditReport", "CommsBudget", "COLLECTIVE_KINDS", "Finding",
    "REGISTRY", "audit_engine", "audit_fn", "check_budget",
    "counts_by_severity", "iter_eqns", "lint_file", "lint_paths",
    "select_rules", "summarize", "worst_severity",
]
