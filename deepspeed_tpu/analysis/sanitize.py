"""Runtime shadow sanitizer for the serving control plane (DSTPU31x).

ASan for KV blocks and request uids: a **shadow table** mirrors every
lifecycle the static DSTPU3xx rules check declaratively
(``analysis/lint/lifecycle.py`` — one spec, two enforcement layers) and
validates each transition as it happens.  Armed, the ``ServingEngine``
calls the hooks below at its alloc/seat/scrub/free/pop/close
boundaries; each hook is pure host-side bookkeeping over Python ints —
nothing touches a traced function, so the compiled decode step is
**byte-identical armed vs off** (proven by the ``--audit-step
serving-lifecycle`` jaxpr-equality stage and the tier-1 twin test, the
same discipline the fault harness and request tracing established).

What it catches (each a typed :class:`~..findings.Finding`):

- **DSTPU310 double-free** — a block freed while the shadow says
  ``free`` (the allocator's own check can be bypassed by a direct
  free-list edit; the shadow cannot).
- **DSTPU311 use-after-free** — a freed (or never-allocated) block
  still referenced by a live sequence's block table, or handed out
  while the shadow says it is already live.
- **DSTPU312 leak-at-close** — blocks still ``allocated``/
  ``quarantined`` when the engine closes.
- **DSTPU313 scratch-block write** — the reserved block 0 entering a
  live slot's block table.
- **DSTPU314 uid double-serve** — one uid's result handed to a caller
  twice (the crash-handoff dedup contract, enforced at the engine).
- **DSTPU315 scrub-while-referenced** — scrubbing/poisoning a block a
  DIFFERENT live sequence still reads (the refcount check the radix
  prefix cache needs; ROADMAP item 1).
- **DSTPU316 scrub-while-shared** — scrubbing/re-zeroing a block the
  prefix cache (or a second co-tenant) still holds a read-only
  reference to: the kv-block FSM allows quarantine only from the
  sole-owner ``allocated`` state, never from ``shared``.
- **DSTPU317 double-import** — a restore imported a private copy of a
  prompt block the PrefixIndex already holds resident: the correct
  path increfs-and-shares the resident block (restore re-share,
  docs/serving.md#disaggregation); importing a duplicate is silent
  pool waste that admission then double-charges.

Arming (OFF by default, resolution highest-wins):
``deepspeed --sanitize`` (launcher) -> env ``DSTPU_SANITIZE`` -> config
``analysis.sanitize.enabled``.  ``halt=True`` (default) raises
:class:`SanitizerError` at the first finding — a lifecycle bug is
corruption in flight, and stopping at the site beats diagnosing the
blast radius; ``halt=False`` collects findings for forensic runs.
"""

import os

from .findings import Finding
from .lint.lifecycle import KV_BLOCK_FSM, REQUEST_FSM  # noqa: F401

# shadow block states — the kv-block FSM's states, verbatim
FREE, ALLOCATED, QUARANTINED, SHARED, COW = KV_BLOCK_FSM["states"]

DOUBLE_FREE = "DSTPU310"
USE_AFTER_FREE = "DSTPU311"
LEAK_AT_CLOSE = "DSTPU312"
SCRATCH_WRITE = "DSTPU313"
DOUBLE_SERVE = "DSTPU314"
SCRUB_REFERENCED = "DSTPU315"
SCRUB_SHARED = "DSTPU316"
DOUBLE_IMPORT = "DSTPU317"

SANITIZER_CODES = (DOUBLE_FREE, USE_AFTER_FREE, LEAK_AT_CLOSE,
                   SCRATCH_WRITE, DOUBLE_SERVE, SCRUB_REFERENCED,
                   SCRUB_SHARED, DOUBLE_IMPORT)


def env_enabled():
    """Tri-state env override: True/False when ``DSTPU_SANITIZE`` is
    set, None when unset (fall through to config)."""
    val = os.environ.get("DSTPU_SANITIZE")
    if val is None:
        return None
    return val.strip().lower() not in ("", "0", "false", "off", "no")


def resolve_enabled(config_enabled=False):
    """The engine's arming decision: env wins over config, config over
    the OFF default."""
    env = env_enabled()
    return bool(config_enabled) if env is None else env


class SanitizerError(RuntimeError):
    """Raised at the first finding when ``halt=True``; carries the
    typed finding so tests (and forensics) see the class, not a
    string."""

    def __init__(self, finding: Finding):
        super().__init__(str(finding))
        self.finding = finding


class ShadowSanitizer:
    """Shadow lifecycle table for one ``BlockAllocator`` + uid table.

    All hooks are O(blocks touched) dict/set updates on host ints —
    call them from host-side scheduler code only, never under trace.
    """

    def __init__(self, num_blocks: int, *, scratch_block: int = 0,
                 halt: bool = True):
        self.num_blocks = int(num_blocks)
        self.scratch_block = int(scratch_block)
        self.halt = bool(halt)
        self.shadow = {b: FREE for b in range(self.num_blocks)}
        self.refs = {}          # block id -> SET of uids referencing it
        self.cache_blocks = set()   # blocks the prefix cache holds a ref on
        self.attached = {}      # uid -> list of block ids in its table
        self.served = set()     # uids whose result left the engine
        self.findings = []
        self.checks = 0         # hook invocations (bench observability)

    # ------------------------------------------------------------ emit
    def _emit(self, code, message, **extra):
        f = Finding(code, "error", message,
                    eqn_path=f"sanitize/{code}", extra=extra)
        self.findings.append(f)
        if self.halt:
            raise SanitizerError(f)

    # ----------------------------------------------------- block hooks
    def on_alloc(self, blocks, uid=None):
        """Allocator handed out ``blocks`` (kv-block FSM free ->
        allocated)."""
        self.checks += 1
        for b in blocks:
            b = int(b)
            if b == self.scratch_block:
                self._emit(SCRATCH_WRITE,
                           f"allocator handed out the reserved scratch "
                           f"block {b}", block=b, uid=uid)
                continue
            if self.shadow.get(b, FREE) != FREE:
                self._emit(USE_AFTER_FREE,
                           f"block {b} allocated while shadow state is "
                           f"{self.shadow.get(b)!r} (held by uid "
                           f"{self.refs.get(b)}) — overlapping tenants",
                           block=b, uid=uid)
                continue
            self.shadow[b] = ALLOCATED

    def on_attach(self, uid, blocks):
        """A live slot's block table now references ``blocks`` for
        ``uid`` (the seat after prefill)."""
        self.checks += 1
        uid = int(uid)
        blocks = [int(b) for b in blocks]
        for b in blocks:
            if b == self.scratch_block:
                self._emit(SCRATCH_WRITE,
                           f"scratch block {self.scratch_block} entered "
                           f"uid {uid}'s live block table — decode "
                           f"writes would corrupt the shared scratch "
                           f"row", block=b, uid=uid)
                continue
            if self.shadow.get(b, FREE) == FREE:
                self._emit(USE_AFTER_FREE,
                           f"uid {uid}'s block table references block "
                           f"{b}, which the shadow says is free — "
                           f"use-after-free", block=b, uid=uid)
                continue
            self.refs.setdefault(b, set()).add(uid)
            self._reshade(b)
        self.attached[uid] = blocks

    def on_detach(self, uid):
        """``uid``'s slot is being torn down; its table rows are about
        to be zeroed."""
        self.checks += 1
        uid = int(uid)
        for b in self.attached.pop(uid, ()):
            holders = self.refs.get(b)
            if holders is not None:
                holders.discard(uid)
                if not holders:
                    del self.refs[b]
            self._reshade(b)

    # ------------------------------------------------- sharing helpers
    def _holder_count(self, b):
        return len(self.refs.get(b, ())) + (1 if b in self.cache_blocks
                                            else 0)

    def _other_holder(self, b, uid):
        """A live uid other than ``uid`` referencing ``b`` (or None)."""
        for h in self.refs.get(b, ()):
            if uid is None or h != int(uid):
                return h
        return None

    def _reshade(self, b):
        """Recompute ALLOCATED vs SHARED from the holder count (the
        kv-block FSM's allocated <-> shared edges)."""
        state = self.shadow.get(b, FREE)
        if state in (FREE, QUARANTINED):
            return
        self.shadow[b] = SHARED if self._holder_count(b) >= 2 \
            else ALLOCATED

    def on_share(self, blocks, uid=None):
        """The prefix cache took a read-only reference on ``blocks``
        (insert at finish, or a restore re-established sharing)."""
        self.checks += 1
        for b in blocks:
            b = int(b)
            if self.shadow.get(b, FREE) in (FREE, QUARANTINED):
                self._emit(USE_AFTER_FREE,
                           f"prefix cache taking a reference on block "
                           f"{b} whose shadow state is "
                           f"{self.shadow.get(b)!r}", block=b, uid=uid)
                continue
            self.cache_blocks.add(b)
            self._reshade(b)

    def on_unshare(self, blocks):
        """The prefix cache dropped its reference (eviction or
        clear)."""
        self.checks += 1
        for b in blocks:
            self.cache_blocks.discard(int(b))
            self._reshade(int(b))

    def on_cow(self, src, dst, uid=None):
        """Copy-on-write: ``uid`` diverged inside shared block ``src``
        and received the fresh private clone ``dst`` (kv-block FSM
        shared -> cow -> allocated for the writer's copy)."""
        self.checks += 1
        src, dst = int(src), int(dst)
        if self.shadow.get(src, FREE) == FREE:
            self._emit(USE_AFTER_FREE,
                       f"copy-on-write from block {src}, which the "
                       f"shadow says is free", block=src, uid=uid)
        if self.shadow.get(dst, FREE) != ALLOCATED:
            self._emit(USE_AFTER_FREE,
                       f"copy-on-write into block {dst} whose shadow "
                       f"state is {self.shadow.get(dst)!r} — the clone "
                       f"must be a fresh private allocation",
                       block=dst, uid=uid)

    def on_quarantine(self, blocks, uid=None):
        """Blocks poisoned/quarantined (kv-block FSM allocated ->
        quarantined)."""
        self.checks += 1
        for b in blocks:
            b = int(b)
            if self.shadow.get(b, FREE) == SHARED \
                    or b in self.cache_blocks:
                self._emit(SCRUB_SHARED,
                           f"quarantining block {b} while shared "
                           f"(holders: uids "
                           f"{sorted(self.refs.get(b, ()))}, cache="
                           f"{b in self.cache_blocks}) — quarantine is "
                           f"legal only from the sole-owner "
                           f"'allocated' state", block=b, uid=uid)
                continue
            holder = self._other_holder(b, uid)
            if holder is not None:
                self._emit(SCRUB_REFERENCED,
                           f"quarantining block {b} still referenced by "
                           f"live uid {holder} (quarantine requested "
                           f"for uid {uid})", block=b, uid=uid,
                           holder=holder)
                continue
            if self.shadow.get(b, FREE) == ALLOCATED:
                self.shadow[b] = QUARANTINED

    def on_scrub(self, blocks, uid=None):
        """Blocks being scrubbed before returning to the pool.
        Scrubbing a block ANOTHER live sequence (or the prefix cache)
        still reads is the refcount violation sharing must never
        commit."""
        self.checks += 1
        for b in blocks:
            b = int(b)
            if self.shadow.get(b, FREE) == SHARED \
                    or b in self.cache_blocks:
                self._emit(SCRUB_SHARED,
                           f"scrubbing block {b} while shared (holders: "
                           f"uids {sorted(self.refs.get(b, ()))}, "
                           f"cache={b in self.cache_blocks}) — its K/V "
                           f"would be zeroed under other tenants",
                           block=b, uid=uid)
                continue
            holder = self._other_holder(b, uid)
            if holder is not None:
                self._emit(SCRUB_REFERENCED,
                           f"scrubbing block {b} while live uid "
                           f"{holder} still references it — its K/V "
                           f"would be zeroed under a running decode",
                           block=b, uid=uid, holder=holder)

    def on_free(self, blocks, uid=None):
        """Blocks returned to the free list (kv-block FSM allocated/
        quarantined -> free).  With sharing armed the allocator only
        reports blocks whose refcount actually hit zero here."""
        self.checks += 1
        for b in blocks:
            b = int(b)
            state = self.shadow.get(b, FREE)
            if state == FREE:
                self._emit(DOUBLE_FREE,
                           f"double free of block {b} (shadow already "
                           f"says free)", block=b, uid=uid)
                continue
            if b in self.cache_blocks:
                self._emit(USE_AFTER_FREE,
                           f"freeing block {b} the prefix cache still "
                           f"holds — cached prefixes would decode from "
                           f"a reused block", block=b, uid=uid)
            holder = self._other_holder(b, uid)
            if holder is not None:
                self._emit(USE_AFTER_FREE,
                           f"freeing block {b} still referenced by live "
                           f"uid {holder} — its table row would decode "
                           f"from a reused block", block=b, uid=uid,
                           holder=holder)
            self.shadow[b] = FREE

    def on_import(self, blocks, uid=None, resident=()):
        """A restore imported wire K/V into the fresh private ``blocks``
        (disaggregated handoff or crash migration).  ``resident`` is
        the engine's evidence list: cache-resident prompt blocks the
        restore imported a DUPLICATE of instead of incref-and-sharing —
        non-empty means the re-share path regressed (DSTPU317).  An
        imported block that the shadow says the cache holds is the same
        defect caught from the other side: wire bytes would overwrite a
        cached prefix under its readers."""
        self.checks += 1
        resident = [int(b) for b in resident]
        if resident:
            self._emit(DOUBLE_IMPORT,
                       f"restore of uid {uid} imported private "
                       f"duplicate(s) of {len(resident)} prefix-cache-"
                       f"resident block(s) {resident[:16]} — the restore "
                       f"path must incref-and-share resident prefixes, "
                       f"not re-import them", blocks=resident[:64],
                       uid=uid)
        for b in blocks:
            b = int(b)
            if b in self.cache_blocks:
                self._emit(DOUBLE_IMPORT,
                           f"restore of uid {uid} imported wire K/V "
                           f"into block {b}, which the prefix cache "
                           f"still holds — cached readers would decode "
                           f"the imported stream's bytes", block=b,
                           uid=uid)

    # ------------------------------------------------------- uid hooks
    def on_serve(self, uid):
        """A result left the engine (request-uid FSM completed ->
        popped; popped is terminal)."""
        self.checks += 1
        uid = int(uid)
        if uid in self.served:
            self._emit(DOUBLE_SERVE,
                       f"uid {uid} served twice — results are "
                       f"pop-once (the crash-handoff dedup contract)",
                       uid=uid)
            return
        self.served.add(uid)

    # ------------------------------------------------------------ close
    def on_close(self):
        """Engine teardown: every block must have come home."""
        self.checks += 1
        leaked = sorted(b for b, s in self.shadow.items() if s != FREE)
        if leaked:
            holders = {b: sorted(self.refs[b]) for b in leaked
                       if self.refs.get(b)}
            self._emit(LEAK_AT_CLOSE,
                       f"{len(leaked)} block(s) still "
                       f"allocated/quarantined at close: {leaked[:16]}"
                       f"{'...' if len(leaked) > 16 else ''}",
                       blocks=leaked[:64], holders={str(k): v for k, v
                                                    in holders.items()
                                                    if v is not None})

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        live = sum(1 for s in self.shadow.values()
                   if s in (ALLOCATED, SHARED))
        shared = sum(1 for s in self.shadow.values() if s == SHARED)
        return {"checks": self.checks, "findings": len(self.findings),
                "live_blocks": live, "shared_blocks": shared,
                "cache_blocks": len(self.cache_blocks),
                "served_uids": len(self.served)}


def describe(config_enabled=False, halt=True) -> dict:
    """Resolved sanitize policy for ``ds_report`` (mirrors the
    comms-compression/monitor describe pattern)."""
    env = env_enabled()
    return {
        "enabled": resolve_enabled(config_enabled),
        "source": ("env DSTPU_SANITIZE" if env is not None
                   else "config analysis.sanitize"
                   if config_enabled else "default (off)"),
        "halt": bool(halt),
        "codes": dict(zip(SANITIZER_CODES,
                          ("double-free", "use-after-free",
                           "leak-at-close", "scratch-block-write",
                           "uid-double-serve",
                           "scrub-while-referenced",
                           "scrub-while-shared",
                           "double-import"))),
    }
