"""CLI: ``python -m deepspeed_tpu.analysis [paths] [--rules ...] [--json]``.

Default invocation lints the installed ``deepspeed_tpu`` package tree
(plus any extra paths given) and exits nonzero on unsuppressed
error-severity findings — the tier-1 suite runs exactly this and gates
on a clean repo.  ``--audit-step`` additionally builds tiny in-memory
engines (z1/z2/z3, bf16) and runs the jaxpr auditor on their real
compiled train steps.
"""

import argparse
import json
import os
import sys

from . import counts_by_severity, lint_paths, select_rules


def _default_paths():
    import deepspeed_tpu
    return [os.path.dirname(os.path.abspath(deepspeed_tpu.__file__))]


class _MLP:
    """Tiny bf16 MLP the built-in audit stages train (CPU works)."""

    def init(self, rng):
        import jax
        import jax.numpy as jnp
        k1, k2 = jax.random.split(rng)
        return {"w1": jax.random.normal(k1, (16, 32), jnp.float32),
                "w2": jax.random.normal(k2, (32, 16), jnp.float32)}

    def loss(self, params, batch, rng):
        import jax.numpy as jnp
        x, y = batch
        h = jnp.maximum(x.astype(jnp.bfloat16) @ params["w1"], 0)
        p = (h @ params["w2"]).astype(jnp.float32)
        return jnp.mean(jnp.square(p - y))


def _audit_builtin_steps(stages):
    """Jaxpr-audit a tiny bf16 MLP engine's compiled step per ZeRO stage
    on whatever devices this process sees (CPU works).

    Each stage is built TWICE through a throwaway compile cache: a cold
    engine populates it, then a WARM-STARTED engine — whose step is the
    deserialized executable — is the one audited.  That makes DSTPU204
    (donation declared vs honored via ``input_output_alias``) hold for
    AOT warm starts, not just fresh compiles (docs/compile-cache.md)."""
    import shutil
    import tempfile
    import numpy as np
    import deepspeed_tpu as ds
    from .findings import Finding
    from .jaxpr_audit import audit_engine

    findings = []
    data = (np.ones((8, 16), np.float32), np.ones((8, 16), np.float32))
    dataset = [(data[0][i], data[1][i]) for i in range(8)]
    # each stage spec pins its own compression policy; an inherited env
    # override would veto the `3q` variant's explicit enabled=true (or
    # silently compress the plain stages)
    os.environ.pop("DSTPU_COMMS_COMPRESSION", None)
    cache_dir = tempfile.mkdtemp(prefix="dstpu-audit-cc-")
    try:
        for spec in stages:
            if str(spec) == "decode":
                findings.extend(_audit_decode_step())
                continue
            if str(spec) == "serving-resilience":
                findings.extend(_audit_serving_resilience())
                continue
            if str(spec) == "serving-lifecycle":
                findings.extend(_audit_serving_lifecycle())
                continue
            if str(spec) == "paged-attn":
                findings.extend(_audit_paged_attention())
                continue
            if str(spec) == "tracing":
                findings.extend(_audit_tracing())
                continue
            if str(spec) == "elastic":
                findings.extend(_audit_elastic_resume())
                continue
            if str(spec) == "moe":
                findings.extend(_audit_moe_step())
                continue
            if str(spec) == "monitor":
                findings.extend(_audit_monitor_step(cache_dir))
                continue
            if str(spec) == "mem":
                findings.extend(_audit_mem_step(cache_dir))
                continue
            if str(spec) == "slo":
                findings.extend(_audit_slo_step(cache_dir))
                continue
            compressed = str(spec).endswith("q")
            stage = int(str(spec).rstrip("q"))
            cfg = {"train_micro_batch_size_per_gpu": 4,
                   "gradient_accumulation_steps": 1,
                   "steps_per_print": 10 ** 9,
                   "bf16": {"enabled": True},
                   "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                   "zero_optimization": {"stage": stage},
                   "compile_cache": {"dir": cache_dir}}
            if compressed:
                # quantized-collectives variant (docs/comms-compression.md):
                # fsdp absorbs the devices so qwZ/qgZ engage whenever this
                # process sees more than one; the audit then additionally
                # gates the census against the engine's declared
                # CommsBudget (wire-byte accounting, DSTPU203)
                cfg["mesh"] = {"axes": {"fsdp": -1, "data": 1}}
                cfg["zero_optimization"][
                    "stage3_param_persistence_threshold"] = 0
                cfg["comms_compression"] = {"enabled": True,
                                            "min_tensor_bytes": 0,
                                            "block_size": 4}
            cold, _, _, _ = ds.initialize(config=cfg, model=_MLP(),
                                          training_data=dataset)
            cache_on = cold.compile_report().get("enabled", False)
            warm_started = False
            if cache_on:
                cold.train_batch()  # compiles + persists the executable
                cold.close()
                engine, _, _, _ = ds.initialize(config=cfg, model=_MLP(),
                                                training_data=dataset)
                engine.train_batch()   # deserializes (or the finding below)
                rep = engine.compile_report()
                warm_started = bool(rep.get("hits"))
                if not warm_started:
                    findings.append(Finding(
                        "DSTPU200", "warning",
                        f"--audit-step z{stage}: warm start did not hit "
                        "the compile cache (hits=0); auditing a fresh "
                        "executable instead of a deserialized one",
                        eqn_path="warm-start",
                        extra={"zero_stage": stage,
                               "compile_report": {k: rep.get(k) for k in
                                                  ("hits", "misses",
                                                   "corrupt",
                                                   "put_errors")}}))
            else:
                # operator kill switch (DSTPU_COMPILE_CACHE=0): audit the
                # cold engine directly — disabling the cache is a choice,
                # not a finding
                engine = cold
            budget = engine.comms_budget() if compressed else None
            report = audit_engine(engine, comms_budget=budget)
            if compressed and budget is not None:
                from .comms import wire_report
                wr = wire_report([c for c in report.census
                                  if c.level == "hlo"])
                if wr["quantized_wire_bytes"] == 0:
                    findings.append(Finding(
                        "DSTPU200", "warning",
                        f"--audit-step z{stage}q: compression routes were "
                        "active but the compiled step moved no quantized "
                        "collective payload",
                        eqn_path="comms-compression",
                        extra={"wire_report": {k: wr[k] for k in
                                               ("wire_bytes",
                                                "quantized_wire_bytes")}}))
            for f in report.findings:
                f.extra = dict(f.extra, zero_stage=stage,
                               compressed=compressed,
                               warm_started=warm_started)
            findings.extend(report.findings)
            engine.close()
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return findings


def _audit_decode_step():
    """Jaxpr-audit the serving layer's fused paged decode step (and the
    InferenceEngine's fused token-scan decode loop) on a tiny GPT-2:
    zero host callbacks (DSTPU201), donation declared-vs-honored on the
    KV pool/cache (DSTPU204), and no weak-scalar recompile hazards
    (DSTPU205) — the serving hot loop must stay a single clean
    executable (docs/serving.md).  The serving step is audited with the
    prefix cache ARMED (docs/serving.md#prefix-sharing): sharing is
    pure host-side block bookkeeping, so the armed decode jaxpr must be
    byte-identical to the cache-off trace."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .findings import Finding
    from .jaxpr_audit import audit_fn
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
    from deepspeed_tpu.inference import (InferenceEngine, ServingEngine,
                                         ServingConfig, Request)

    cfg = GPT2Config(vocab_size=64, max_seq=32, n_embd=32, n_layer=2,
                     n_head=4, embd_pdrop=0.0, attn_pdrop=0.0,
                     resid_pdrop=0.0, attention_impl="jnp")
    model = GPT2(cfg, dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0))
    findings = []
    for kv_bits in (16, 8):
        scfg = dict(batch_slots=2, block_size=8, kv_bits=kv_bits,
                    max_new_tokens=4, preflight=False)
        plain = ServingEngine(model=model, params=params,
                              config=ServingConfig(**scfg))
        plain._build_decode()
        plain_jaxpr = str(jax.make_jaxpr(plain._decode)(
            *plain._decode_args()))
        plain.close()
        srv = ServingEngine(
            model=model, params=params,
            config=ServingConfig(prefix_cache=True, **scfg))
        srv._build_decode()
        if str(jax.make_jaxpr(srv._decode)(
                *srv._decode_args())) != plain_jaxpr:
            findings.append(Finding(
                "DSTPU201", "error",
                "--audit-step decode: arming serving.prefix_cache "
                f"CHANGED the traced decode step (kv_bits={kv_bits}) — "
                "sharing must stay host-side block bookkeeping, never "
                "program content", eqn_path="serving/jaxpr-equality"))
        # a shared-prefix pair warms the executables audit_fn will
        # inspect AND takes a real radix-cache hit, so the step audited
        # below is the one that served shared blocks
        srv.run([Request(tokens=np.arange(12), max_new_tokens=2, uid=1),
                 Request(tokens=np.concatenate(
                     [np.arange(8), np.array([33, 34, 35, 36])]),
                     max_new_tokens=2, uid=2)])
        if not srv.stats()["prefix_cache"]["requests_hit"]:
            findings.append(Finding(
                "DSTPU200", "warning",
                "--audit-step decode: the shared-prefix pair produced "
                f"no radix-cache hit (kv_bits={kv_bits}) — the audited "
                "step never exercised sharing",
                eqn_path="serving/prefix-cache"))
        report = audit_fn(srv._decode, *srv._decode_args(),
                          donate_argnums=(1,), mesh=srv.engine.mesh)
        for f in report.findings:
            f.extra = dict(f.extra, audit="serving-decode",
                           kv_bits=kv_bits)
        findings.extend(report.findings)
        srv.close()
    # the generate() fused token scan (prefill + ONE scan executable)
    eng = InferenceEngine(model, params=params)
    eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=4)
    loop = next(iter(eng._decode_loops.values()))
    cache = model.init_cache(1, 8)
    last = jnp.zeros((1, cfg.vocab_size), jnp.float32)
    report = audit_fn(loop, eng.params, last, cache,
                      jax.random.PRNGKey(0), jnp.float32(1.0),
                      donate_argnums=(2,), mesh=eng.mesh)
    for f in report.findings:
        # the decode loop DISCARDS the final cache (tokens are the only
        # output), so jax cannot alias the donated cache to an output —
        # a known, documented non-aliasing, not a regression (DSTPU204
        # flags declared-but-unhonored donation)
        if f.rule == "DSTPU204":
            continue
        f.extra = dict(f.extra, audit="generate-decode-loop")
        findings.append(f)
    eng.close()
    return findings


def _audit_serving_resilience():
    """--audit-step serving-resilience: the quarantine-sentinel-armed
    serving decode step (docs/serving.md#resilience) must stay one clean
    executable — zero host callbacks (DSTPU201) with the pool donation
    honored (DSTPU204) — and the ``logit_nan`` chaos fault must leave
    the TRACED program byte-identical (the poison rides the pool data;
    the PR-3 jaxpr-equality discipline applied to the serving step).
    Functionally, a poisoned request must come back quarantined while
    its neighbor completes."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .findings import Finding
    from .jaxpr_audit import audit_fn
    from deepspeed_tpu import fault
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
    from deepspeed_tpu.inference import (ServingEngine, ServingConfig,
                                         Request, POISONED, OK)

    cfg = GPT2Config(vocab_size=64, max_seq=32, n_embd=32, n_layer=2,
                     n_head=4, embd_pdrop=0.0, attn_pdrop=0.0,
                     resid_pdrop=0.0, attention_impl="jnp")
    model = GPT2(cfg, dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0))
    scfg = dict(batch_slots=2, block_size=8, max_new_tokens=4,
                preflight=False)
    findings = []

    def jaxpr_text(srv):
        srv._build_decode()
        return str(jax.make_jaxpr(srv._decode)(*srv._decode_args()))

    fault.reset()
    try:
        clean = ServingEngine(model=model, params=params,
                              config=ServingConfig(**scfg))
        clean_jaxpr = jaxpr_text(clean)
        # audit the sentinel-armed step itself: no host callbacks, pool
        # donation honored through the quarantine sentinel's extra output
        clean.run([Request(tokens=np.arange(5), max_new_tokens=2)])
        report = audit_fn(clean._decode, *clean._decode_args(),
                          donate_argnums=(1,), mesh=clean.engine.mesh)
        for f in report.findings:
            f.extra = dict(f.extra, audit="serving-resilience")
        findings.extend(report.findings)
        clean.close()

        fault.configure(logit_nan=7)
        armed = ServingEngine(model=model, params=params,
                              config=ServingConfig(**scfg))
        if jaxpr_text(armed) != clean_jaxpr:
            findings.append(Finding(
                "DSTPU201", "error",
                "--audit-step serving-resilience: arming the logit_nan "
                "fault CHANGED the traced decode step (jaxpr armed != "
                "disarmed) — the poison must ride the pool data, never "
                "the program", eqn_path="serving/jaxpr-equality"))
        res = armed.run([Request(tokens=np.arange(5), uid=7),
                         Request(tokens=np.arange(6), uid=8)])
        if res[7]["outcome"] != POISONED or res[8]["outcome"] != OK:
            findings.append(Finding(
                "DSTPU200", "warning",
                "--audit-step serving-resilience: the poisoned request "
                f"was not quarantined (outcomes: uid7="
                f"{res[7]['outcome']}, uid8={res[8]['outcome']})",
                eqn_path="serving/quarantine"))
        armed.close()
    finally:
        fault.reset()
    return findings


def _audit_serving_lifecycle():
    """--audit-step serving-lifecycle: the three lifecycle layers
    (docs/static-analysis.md#lifecycle) proven against live engines:

    - **jaxpr parity** — twin tiny serving engines, shadow sanitizer
      armed vs off, must trace byte-identical decode steps AND produce
      token-identical results (the sanitizer is host-side bookkeeping,
      never program content);
    - **detector integrity** — every DSTPU31x violation class, driven
      synthetically against a :class:`ShadowSanitizer`, must be caught
      (a sanitizer that misses a seeded double-free proves nothing
      about a clean run);
    - **interleaving sweeps** — the full 720-ordering
      :func:`~.interleave.crash_handoff_scenario` permutation sweep
      over the real router, the 720-ordering
      :func:`~.interleave.disagg_handoff_scenario` prefill→decode
      handoff sweep (publish/announce/torn-publish/crash racing), and
      the 720-ordering :func:`~.interleave.prefix_sharing_scenario`
      refcount sweep over the real allocator + radix cache, must all
      report zero violations."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .findings import Finding
    from . import sanitize
    from .interleave import (explore, disagg_handoff_scenario,
                             prefix_sharing_scenario)
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
    from deepspeed_tpu.inference import (ServingEngine, ServingConfig,
                                         Request)

    findings = []

    # ---- detector integrity: every class must fire ------------------
    def seeded(code, drive):
        san = sanitize.ShadowSanitizer(8, halt=False)
        drive(san)
        got = [f.rule for f in san.findings]
        if code not in got:
            findings.append(Finding(
                "DSTPU200", "error",
                f"--audit-step serving-lifecycle: the shadow sanitizer "
                f"MISSED a seeded {code} violation (got {got}) — the "
                f"armed run's clean verdict below proves nothing",
                eqn_path=f"sanitize/detector/{code}"))

    seeded(sanitize.DOUBLE_FREE,
           lambda s: (s.on_alloc([3]), s.on_free([3]), s.on_free([3])))
    seeded(sanitize.USE_AFTER_FREE,
           lambda s: s.on_attach(1, [3]))
    seeded(sanitize.LEAK_AT_CLOSE,
           lambda s: (s.on_alloc([3]), s.on_close()))
    seeded(sanitize.SCRATCH_WRITE,
           lambda s: (s.on_alloc([3]), s.on_attach(1, [0, 3])))
    seeded(sanitize.DOUBLE_SERVE,
           lambda s: (s.on_serve(5), s.on_serve(5)))
    seeded(sanitize.SCRUB_REFERENCED,
           lambda s: (s.on_alloc([3]), s.on_attach(1, [3]),
                      s.on_scrub([3], uid=2)))
    seeded(sanitize.SCRUB_SHARED,
           lambda s: (s.on_alloc([3]), s.on_share([3]),
                      s.on_scrub([3], uid=1)))
    seeded(sanitize.DOUBLE_IMPORT,
           lambda s: (s.on_alloc([2, 3]),
                      s.on_import([3], uid=1, resident=[2])))

    # ---- jaxpr parity + token identity: armed vs off ----------------
    cfg = GPT2Config(vocab_size=64, max_seq=32, n_embd=32, n_layer=2,
                     n_head=4, embd_pdrop=0.0, attn_pdrop=0.0,
                     resid_pdrop=0.0, attention_impl="jnp")
    model = GPT2(cfg, dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0))
    scfg = dict(batch_slots=2, block_size=8, max_new_tokens=4,
                preflight=False)

    def run(sanitize_on):
        srv = ServingEngine(
            model=model, params=params,
            config=ServingConfig(sanitize=sanitize_on, **scfg))
        res = srv.run([Request(tokens=np.arange(5), max_new_tokens=3,
                               uid=1),
                       Request(tokens=np.arange(6) % 3, max_new_tokens=2,
                               uid=2)])
        srv._build_decode()
        jx = str(jax.make_jaxpr(srv._decode)(*srv._decode_args()))
        stats = srv.stats()
        srv.close()
        return res, jx, stats

    res_off, jx_off, _ = run(False)
    res_on, jx_on, stats_on = run(True)
    if jx_on != jx_off:
        findings.append(Finding(
            "DSTPU201", "error",
            "--audit-step serving-lifecycle: arming the shadow "
            "sanitizer CHANGED the traced decode step (jaxpr armed != "
            "off) — the shadow table must stay host-side bookkeeping",
            eqn_path="sanitize/jaxpr-equality"))
    for uid in (1, 2):
        if res_on[uid]["tokens"] != res_off[uid]["tokens"]:
            findings.append(Finding(
                "DSTPU201", "error",
                f"--audit-step serving-lifecycle: uid {uid} tokens "
                f"differ armed vs off — the sanitizer perturbed the "
                f"computation", eqn_path="sanitize/token-identity"))
    # roles armed (docs/serving.md#disaggregation): the whole handoff
    # is host-side file I/O — a decode-role worker with the transfer
    # queue armed must trace the SAME decode step as the mixed engine
    import tempfile
    with tempfile.TemporaryDirectory(prefix="dstpu-disagg-") as td:
        srv = ServingEngine(
            model=model, params=params,
            config=ServingConfig(role="decode",
                                 transfer={"dir": td}, **scfg))
        srv._build_decode()
        jx_role = str(jax.make_jaxpr(srv._decode)(*srv._decode_args()))
        srv.close()
    if jx_role != jx_off:
        findings.append(Finding(
            "DSTPU201", "error",
            "--audit-step serving-lifecycle: arming serving.role/"
            "transfer CHANGED the traced decode step (jaxpr decode-"
            "role != mixed) — the transfer plane must stay host-side "
            "file I/O", eqn_path="transfer/jaxpr-equality"))
    san_stats = stats_on.get("sanitizer") or {}
    if san_stats.get("findings", 0):
        findings.append(Finding(
            "DSTPU200", "error",
            f"--audit-step serving-lifecycle: the armed clean run "
            f"raised {san_stats['findings']} sanitizer finding(s)",
            eqn_path="sanitize/clean-run", extra={"stats": san_stats}))
    if not san_stats.get("checks", 0):
        findings.append(Finding(
            "DSTPU200", "error",
            "--audit-step serving-lifecycle: the armed run performed "
            "ZERO sanitizer checks — the hooks are not wired",
            eqn_path="sanitize/clean-run"))

    # ---- interleaving sweeps ----------------------------------------
    for report in (explore(), explore(disagg_handoff_scenario()),
                   explore(prefix_sharing_scenario())):
        if not report["ok"]:
            findings.extend(report["findings"])
        if report["explored"] != report["total_permutations"]:
            findings.append(Finding(
                "DSTPU200", "error",
                f"--audit-step serving-lifecycle: "
                f"{report['scenario']} interleave sweep covered "
                f"{report['explored']}/{report['total_permutations']} "
                f"orderings — the sweep must be exhaustive",
                eqn_path="interleave/coverage"))
    return findings


def _audit_tracing():
    """--audit-step tracing: request-scoped tracing armed at
    ``trace_sample_rate=1.0`` (docs/monitoring.md#request-tracing) must
    leave the serving decode step byte-identical — tracing is host-side
    bookkeeping, never program content.  Gates: armed-vs-disarmed jaxpr
    equality, zero host callbacks (DSTPU201) and pool donation honored
    (DSTPU204) on the armed step, and the armed run must emit parseable
    ``trace`` events with monotone non-overlapping spans."""
    import shutil
    import tempfile
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .findings import Finding
    from .jaxpr_audit import audit_fn
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
    from deepspeed_tpu.inference import (ServingEngine, ServingConfig,
                                         Request)
    from deepspeed_tpu.monitor import Monitor, parse_line
    from deepspeed_tpu.monitor.sinks import EVENTS_FILE

    cfg = GPT2Config(vocab_size=64, max_seq=32, n_embd=32, n_layer=2,
                     n_head=4, embd_pdrop=0.0, attn_pdrop=0.0,
                     resid_pdrop=0.0, attention_impl="jnp")
    model = GPT2(cfg, dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0))
    scfg = dict(batch_slots=2, block_size=8, max_new_tokens=4,
                preflight=False)
    findings = []

    def jaxpr_text(srv):
        srv._build_decode()
        return str(jax.make_jaxpr(srv._decode)(*srv._decode_args()))

    clean = ServingEngine(model=model, params=params,
                          config=ServingConfig(**scfg))
    clean_jaxpr = jaxpr_text(clean)
    clean.close()

    run_dir = tempfile.mkdtemp(prefix="dstpu-audit-tracing-")
    try:
        armed = ServingEngine(
            model=model, params=params,
            monitor=Monitor(run_dir=run_dir, role="serving"),
            config=ServingConfig(trace_sample_rate=1.0, **scfg))
        if jaxpr_text(armed) != clean_jaxpr:
            findings.append(Finding(
                "DSTPU201", "error",
                "--audit-step tracing: arming trace_sample_rate=1.0 "
                "CHANGED the traced decode step (jaxpr armed != "
                "disarmed) — tracing must stay host-side bookkeeping",
                eqn_path="tracing/jaxpr-equality"))
        armed.run([Request(tokens=np.arange(5), max_new_tokens=3),
                   Request(tokens=np.arange(6), max_new_tokens=2)])
        report = audit_fn(armed._decode, *armed._decode_args(),
                          donate_argnums=(1,), mesh=armed.engine.mesh)
        for f in report.findings:
            f.extra = dict(f.extra, audit="tracing")
        findings.extend(report.findings)
        armed.close()
        traces = []
        stream_ok = True
        try:
            with open(os.path.join(run_dir, EVENTS_FILE)) as fh:
                for line in fh:
                    if line.strip():
                        e = parse_line(line)
                        if e.kind == "trace":
                            traces.append(e)
        except (OSError, ValueError) as e:
            stream_ok = False
            findings.append(Finding(
                "DSTPU104", "error",
                f"--audit-step tracing: armed event stream did not "
                f"parse ({e})", eqn_path="tracing/stream"))
        if stream_ok and not traces:
            findings.append(Finding(
                "DSTPU104", "error",
                "--audit-step tracing: the armed run emitted no `trace` "
                "events at trace_sample_rate=1.0",
                eqn_path="tracing/stream"))
        for e in traces:
            prev = 0.0
            for s in e.fields.get("spans") or ():
                if s["start_ms"] < prev - 1e-6:
                    findings.append(Finding(
                        "DSTPU104", "error",
                        f"--audit-step tracing: request "
                        f"{e.fields.get('uid')} spans overlap/regress "
                        f"({s['name']} starts {s['start_ms']}ms before "
                        f"the previous span ended at {prev}ms)",
                        eqn_path="tracing/spans"))
                prev = max(prev, s["start_ms"] + s["dur_ms"])
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)
    return findings


def _kv_gather_eqns(closed_jaxpr, block_size, n_head, head_dim):
    """Gathered-K/V-materialization census: every ``gather`` equation
    (anywhere in the program, scan bodies included) whose output is a
    per-slot block-list materialization — rank >= 5 with trailing dims
    ``(block_size, n_head, head_dim)``, the exact shape
    ``paged_kv.gather_kv``'s table gather produces.  The in-place
    kernel's decode step must contain ZERO of these; the gather
    fallback's must contain them (the detector is sanity-checked
    against the fallback so an upstream lowering change cannot silently
    blind it)."""
    from .jaxpr_audit import iter_eqns
    hits = []
    sig = (int(block_size), int(n_head), int(head_dim))
    for eqn, path in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "gather":
            continue
        for ov in eqn.outvars:
            shape = tuple(getattr(ov.aval, "shape", ()))
            if len(shape) >= 5 and shape[-3:] == sig:
                hits.append((path, shape))
    return hits


def _audit_paged_attention():
    """--audit-step paged-attn: the in-place paged-attention kernel
    decode step (docs/serving.md#paged-attention-kernel) must be one
    clean executable — zero host callbacks (DSTPU201), pool donation
    honored (DSTPU204) — with **no gathered K/V materialization in the
    jaxpr** (the census above; the gather-fallback twin must trip the
    same census, proving the detector sees what the kernel deleted).
    Speculative decoding armed must (a) keep the armed scoring step
    just as clean and (b) produce TOKEN-IDENTICAL outputs to the
    disarmed engine (greedy and sampled) — the determinism contract's
    acceptance-semantics half."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .findings import Finding
    from .jaxpr_audit import audit_fn
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
    from deepspeed_tpu.inference import (ServingEngine, ServingConfig,
                                         Request)

    bs, H = 8, 4
    params_cache = {}

    def build(paged_impl, speculative=None, kv_bits=16):
        cfg = GPT2Config(vocab_size=64, max_seq=32, n_embd=32, n_layer=2,
                         n_head=H, embd_pdrop=0.0, attn_pdrop=0.0,
                         resid_pdrop=0.0, attention_impl="jnp",
                         paged_attention_impl=paged_impl)
        model = GPT2(cfg, dtype=jnp.bfloat16)
        if "p" not in params_cache:
            params_cache["p"] = model.init(jax.random.PRNGKey(0))
        return ServingEngine(
            model=model, params=params_cache["p"],
            config=ServingConfig(batch_slots=2, block_size=bs,
                                 kv_bits=kv_bits, max_new_tokens=6,
                                 preflight=False,
                                 speculative=speculative))

    findings = []
    hd = 32 // H

    # (1) kernel decode step, 16-bit and int8 pools: clean audit + the
    # zero-gather census
    for kv_bits in (16, 8):
        srv = build("kernel", kv_bits=kv_bits)
        srv.run([Request(tokens=np.arange(5), max_new_tokens=2)])
        report = audit_fn(srv._decode, *srv._decode_args(),
                          donate_argnums=(1,), mesh=srv.engine.mesh)
        for f in report.findings:
            f.extra = dict(f.extra, audit="paged-attn", kv_bits=kv_bits)
        findings.extend(report.findings)
        jaxpr = jax.make_jaxpr(srv._decode)(*srv._decode_args())
        hits = _kv_gather_eqns(jaxpr, bs, H, hd)
        if hits:
            findings.append(Finding(
                "DSTPU206", "error",
                f"--audit-step paged-attn: the kernel decode step "
                f"(kv{kv_bits}) still materializes gathered K/V "
                f"({len(hits)} gather eqn(s), e.g. {hits[0][1]} at "
                f"{hits[0][0]}) — the in-place kernel must read pool "
                f"blocks without a dense per-slot copy",
                eqn_path="paged-attn/zero-gather"))
        srv.close()

    # detector sanity: the gather fallback MUST trip the census
    srv_g = build("gather")
    srv_g._build_decode()
    jaxpr_g = jax.make_jaxpr(srv_g._decode)(*srv_g._decode_args())
    if not _kv_gather_eqns(jaxpr_g, bs, H, hd):
        findings.append(Finding(
            "DSTPU206", "error",
            "--audit-step paged-attn: the gather-fallback twin shows NO "
            "gathered K/V materialization — the census detector is "
            "blind and the kernel's zero-gather verdict above proves "
            "nothing", eqn_path="paged-attn/census-sanity"))
    srv_g.close()

    # (2) speculative decode: armed engine == disarmed engine, token
    # for token (greedy AND sampled), and the armed step audits clean
    def traffic():
        return [Request(tokens=np.tile(np.arange(4), 3),
                        max_new_tokens=6, uid=1),
                Request(tokens=np.arange(5) % 3, max_new_tokens=5,
                        uid=2, do_sample=True, temperature=0.8, seed=7)]

    plain_srv = build("kernel")
    plain = plain_srv.run(traffic())
    plain_srv.close()
    spec_srv = build("kernel", speculative={"k": 3})
    spec = spec_srv.run(traffic())
    for uid in (1, 2):
        if plain[uid]["tokens"] != spec[uid]["tokens"]:
            findings.append(Finding(
                "DSTPU200", "error",
                f"--audit-step paged-attn: speculative decode diverged "
                f"from the autoregressive path on uid {uid} "
                f"(plain={plain[uid]['tokens']}, "
                f"spec={spec[uid]['tokens']}) — acceptance must be "
                f"'the token the model would have sampled anyway'",
                eqn_path="paged-attn/spec-equivalence"))
    report = audit_fn(spec_srv._decode, *spec_srv._decode_args(),
                      donate_argnums=(1,), mesh=spec_srv.engine.mesh)
    for f in report.findings:
        f.extra = dict(f.extra, audit="paged-attn-spec")
    findings.extend(report.findings)
    jaxpr_s = jax.make_jaxpr(spec_srv._decode)(*spec_srv._decode_args())
    if _kv_gather_eqns(jaxpr_s, bs, H, hd):
        findings.append(Finding(
            "DSTPU206", "error",
            "--audit-step paged-attn: the speculative scoring step "
            "materializes gathered K/V — the kernel path must cover "
            "multi-token windows too",
            eqn_path="paged-attn/spec-zero-gather"))
    spec_srv.close()
    return findings


def _audit_moe_step():
    """--audit-step moe: jaxpr-audit the quantized expert-parallel
    dispatch (docs/comms-compression.md, moe route) on a data×expert
    mesh: the compiled step must run zero host callbacks (DSTPU201)
    with every donation honored (DSTPU204), its census must move the
    dispatch/combine payload as int8 with replica groups > 1 on the
    expert phase (the two-level split), fit the engine's declared
    CommsBudget — and that budget must be TIGHT: the full-width twin's
    census has to violate it."""
    import numpy as np
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel.mesh import make_mesh
    from .findings import Finding
    from .fixtures import MoEProbeModel
    from .jaxpr_audit import audit_engine
    from .comms import wire_report, check_budget

    n = jax.device_count()
    if n < 4 or n % 2:
        return [Finding(
            "DSTPU200", "warning",
            f"--audit-step moe needs an even device count >= 4 for the "
            f"data×expert mesh (got {n}); skipped", eqn_path="moe-dispatch")]
    mesh = make_mesh({"data": 2, "expert": n // 2})
    rng = np.random.default_rng(0)
    # big enough that the expert exchange dominates the budget floors:
    # the tightness check below needs the full-width dispatch's 4x-wider
    # payload to clear the int8 ceiling by a margin, not a whisker
    dim = 128
    data = [(rng.normal(size=(dim,)).astype(np.float32),
             rng.normal(size=(dim,)).astype(np.float32)) for _ in range(512)]
    base = {"train_micro_batch_size_per_gpu": 64,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10 ** 9,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1}}

    def build(comp):
        cfg = dict(base)
        if comp:
            cfg["comms_compression"] = {
                "enabled": True, "min_tensor_bytes": 0,
                "routes": ["moe"], "moe": {"bits": 8, "block_size": 64}}
        e, _, _, _ = ds.initialize(config=cfg, model=MoEProbeModel(dim, n),
                                   training_data=data, mesh=mesh)
        e.train_batch()      # cold trace records the moe wire expectation
        return e

    findings = []
    full = build(False)
    full_census = [c for c in audit_engine(full).census if c.level == "hlo"]
    full.close()

    engine = build(True)
    if not engine._router.moe_active:
        engine.close()
        return [Finding("DSTPU200", "warning",
                        "--audit-step moe: the moe route did not activate "
                        "on this mesh", eqn_path="moe-dispatch",
                        extra={"policy": engine._router.describe()})]
    budget = engine.comms_budget()
    report = audit_engine(engine, comms_budget=budget)
    hlo = [c for c in report.census if c.level == "hlo"]
    wr = wire_report(hlo)
    quant = [c for c in hlo if c.quantized]
    if not quant:
        findings.append(Finding(
            "DSTPU200", "warning",
            "--audit-step moe: expert dispatch moved no int8 payload",
            eqn_path="moe-dispatch",
            extra={"by_kind": wr["by_kind"]}))
    if quant and not any(c.groups > 1 for c in quant):
        findings.append(Finding(
            "DSTPU200", "warning",
            "--audit-step moe: no quantized collective ran with replica "
            "groups > 1 (two-level phase missing on the data×expert mesh)",
            eqn_path="moe-dispatch",
            extra={"groups": [c.groups for c in quant]}))
    if budget is None or not check_budget(full_census, budget):
        findings.append(Finding(
            "DSTPU200", "warning",
            "--audit-step moe: the declared budget is loose — the "
            "full-width twin's census fits it",
            eqn_path="moe-dispatch",
            extra={"budget_declared": budget is not None}))
    for f in report.findings:
        f.extra = dict(f.extra, audit="moe-dispatch")
    findings.extend(report.findings)
    engine.close()
    return findings


def _audit_monitor_step(cache_dir):
    """--audit-step monitor: prove that an ARMED monitor leaves the
    compiled train step clean (docs/monitoring.md).  Twin tiny engines
    — monitor off and monitor on (jsonl+ring sinks into a tmp dir) —
    must produce byte-identical ``_train_step`` jaxprs (the PR-3
    equality gate), the armed engine's compiled step must show zero
    DSTPU201 host callbacks, and the stream it wrote must parse line by
    line under the versioned schema."""
    import shutil
    import tempfile
    import numpy as np
    import deepspeed_tpu as ds
    from deepspeed_tpu.monitor import parse_line
    from deepspeed_tpu.monitor.sinks import EVENTS_FILE
    from .findings import Finding
    from .jaxpr_audit import audit_engine, train_step_jaxpr_text

    data = (np.ones((8, 16), np.float32), np.ones((8, 16), np.float32))
    dataset = [(data[0][i], data[1][i]) for i in range(8)]
    mon_dir = tempfile.mkdtemp(prefix="dstpu-audit-mon-")
    findings = []

    def build(mon_cfg):
        cfg = {"train_micro_batch_size_per_gpu": 4,
               "gradient_accumulation_steps": 1,
               "steps_per_print": 10 ** 9,
               "bf16": {"enabled": True},
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 2},
               "compile_cache": {"dir": cache_dir}}
        if mon_cfg:
            cfg["monitor"] = mon_cfg
        return ds.initialize(config=cfg, model=_MLP(),
                             training_data=dataset)[0]

    try:
        off = build(None)
        armed = build({"enabled": True, "dir": mon_dir,
                       "sinks": ["jsonl", "ring"], "interval": 1})

        if train_step_jaxpr_text(off) != train_step_jaxpr_text(armed):
            findings.append(Finding(
                "DSTPU201", "error",
                "--audit-step monitor: the armed monitor CHANGED the "
                "traced train step (jaxpr monitor-on != monitor-off) — "
                "instrumentation leaked into the compiled program",
                eqn_path="monitor/jaxpr-equality"))
        off.close()

        armed.train_batch()
        armed.train_batch()
        report = audit_engine(armed)
        for f in report.findings:
            f.extra = dict(f.extra, audit="monitor-armed")
        findings.extend(report.findings)
        armed.monitor.flush()
        stream = os.path.join(mon_dir, EVENTS_FILE)
        try:
            events = [parse_line(ln) for ln in open(stream)
                      if ln.strip()]
        except Exception as e:
            events = None
            findings.append(Finding(
                "DSTPU200", "warning",
                f"--audit-step monitor: event stream did not parse ({e})",
                eqn_path="monitor/stream"))
        if events is not None:
            kinds = {e.kind for e in events}
            missing = {"step", "span"} - kinds
            if missing:
                findings.append(Finding(
                    "DSTPU200", "warning",
                    f"--audit-step monitor: armed run emitted no "
                    f"{sorted(missing)} events (got {sorted(kinds)})",
                    eqn_path="monitor/stream"))
        armed.close()
    finally:
        shutil.rmtree(mon_dir, ignore_errors=True)
    return findings


def _audit_mem_step(cache_dir):
    """--audit-step mem: the memory ledger must stay host-side
    bookkeeping (docs/monitoring.md#memory-explainability).  Gates:

    - twin tiny TRAIN engines — ledger armed (``monitor.memory_interval
      = 1``) vs monitor off — produce byte-identical ``_train_step``
      jaxprs, and the armed engine's compiled step shows zero DSTPU201
      host callbacks;
    - twin SERVING engines — armed vs disarmed — produce byte-identical
      decode-step jaxprs;
    - both armed streams carry parseable schema-v3 ``mem`` events whose
      attribution names the expected subsystems (params / master /
      moments on the train side, the paged-KV pool on the serving side)
      and whose residual fields are present."""
    import shutil
    import tempfile
    import numpy as np
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.monitor import Monitor, parse_line
    from deepspeed_tpu.monitor.sinks import EVENTS_FILE
    from .findings import Finding
    from .jaxpr_audit import audit_engine, train_step_jaxpr_text

    findings = []

    def read_mems(run_dir, what):
        mems = []
        try:
            with open(os.path.join(run_dir, EVENTS_FILE)) as fh:
                for line in fh:
                    if line.strip():
                        e = parse_line(line)
                        if e.kind == "mem":
                            mems.append(e)
        except (OSError, ValueError) as e:
            findings.append(Finding(
                "DSTPU104", "error",
                f"--audit-step mem: {what} event stream did not parse "
                f"({e})", eqn_path="mem/stream"))
            return None
        if not mems:
            findings.append(Finding(
                "DSTPU104", "error",
                f"--audit-step mem: the armed {what} run emitted no "
                "`mem` events", eqn_path="mem/stream"))
        return mems

    # ---- train twin --------------------------------------------------
    data = (np.ones((8, 16), np.float32), np.ones((8, 16), np.float32))
    dataset = [(data[0][i], data[1][i]) for i in range(8)]
    mon_dir = tempfile.mkdtemp(prefix="dstpu-audit-mem-")

    def build(mon_cfg):
        cfg = {"train_micro_batch_size_per_gpu": 4,
               "gradient_accumulation_steps": 1,
               "steps_per_print": 10 ** 9,
               "bf16": {"enabled": True},
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 2},
               "compile_cache": {"dir": cache_dir}}
        if mon_cfg:
            cfg["monitor"] = mon_cfg
        return ds.initialize(config=cfg, model=_MLP(),
                             training_data=dataset)[0]

    try:
        off = build(None)
        armed = build({"enabled": True, "dir": mon_dir,
                       "sinks": ["jsonl"], "interval": 1,
                       "memory_interval": 1})
        if train_step_jaxpr_text(off) != train_step_jaxpr_text(armed):
            findings.append(Finding(
                "DSTPU201", "error",
                "--audit-step mem: arming the memory ledger CHANGED the "
                "traced train step (jaxpr ledger-on != ledger-off) — "
                "attribution leaked into the compiled program",
                eqn_path="mem/jaxpr-equality"))
        off.close()
        armed.train_batch()
        armed.train_batch()
        report = audit_engine(armed)
        for f in report.findings:
            f.extra = dict(f.extra, audit="mem-armed")
        findings.extend(report.findings)
        armed.monitor.flush()
        mems = read_mems(mon_dir, "train")
        if mems:
            fields = mems[-1].fields
            hbm = fields.get("hbm") or {}
            missing = {"params", "master_fp32", "opt_moments"} - set(hbm)
            if missing:
                findings.append(Finding(
                    "DSTPU104", "error",
                    f"--audit-step mem: train ledger attribution is "
                    f"missing {sorted(missing)} (got {sorted(hbm)})",
                    eqn_path="mem/attribution"))
            if "host_residual_bytes" not in fields:
                findings.append(Finding(
                    "DSTPU104", "warning",
                    "--audit-step mem: no host residual in the train "
                    "ledger (host RSS unreadable?)",
                    eqn_path="mem/residual"))
        armed.close()
    finally:
        shutil.rmtree(mon_dir, ignore_errors=True)

    # ---- serving twin ------------------------------------------------
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
    from deepspeed_tpu.inference import (ServingEngine, ServingConfig,
                                         Request)
    cfg = GPT2Config(vocab_size=64, max_seq=32, n_embd=32, n_layer=2,
                     n_head=4, embd_pdrop=0.0, attn_pdrop=0.0,
                     resid_pdrop=0.0, attention_impl="jnp")
    model = GPT2(cfg, dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0))
    scfg = dict(batch_slots=2, block_size=8, max_new_tokens=4,
                preflight=False)

    def decode_jaxpr(srv):
        srv._build_decode()
        return str(jax.make_jaxpr(srv._decode)(*srv._decode_args()))

    clean = ServingEngine(model=model, params=params,
                          config=ServingConfig(**scfg))
    clean_jaxpr = decode_jaxpr(clean)
    clean.close()
    run_dir = tempfile.mkdtemp(prefix="dstpu-audit-mem-srv-")
    try:
        armed = ServingEngine(
            model=model, params=params,
            monitor=Monitor(run_dir=run_dir, role="serving"),
            config=ServingConfig(**scfg))
        if decode_jaxpr(armed) != clean_jaxpr:
            findings.append(Finding(
                "DSTPU201", "error",
                "--audit-step mem: arming the monitor+ledger CHANGED "
                "the traced decode step (jaxpr armed != disarmed)",
                eqn_path="mem/jaxpr-equality"))
        # enough decode steps to cross the serving ledger cadence
        armed.run([Request(tokens=np.arange(4), max_new_tokens=18,
                           uid=u) for u in range(2)])
        armed.close()
        mems = read_mems(run_dir, "serving")
        if mems:
            hbm = mems[-1].fields.get("hbm") or {}
            if "paged_kv_pool" not in hbm:
                findings.append(Finding(
                    "DSTPU104", "error",
                    f"--audit-step mem: serving ledger attribution is "
                    f"missing the paged_kv_pool (got {sorted(hbm)})",
                    eqn_path="mem/attribution"))
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)
    return findings


def _audit_slo_step(cache_dir):
    """--audit-step slo: the SLO engine must stay host-side stream
    consumption (docs/monitoring.md#slo-tracking).  Gates:

    - twin tiny TRAIN engines — ``monitor.slo`` armed (objectives over
      tokens/s + MFU, the training floors) vs monitor off — produce
      byte-identical ``_train_step`` jaxprs, and the armed engine's
      compiled step shows zero DSTPU201 host callbacks;
    - twin SERVING engines — armed (p99/error-rate objectives) vs
      disarmed — produce byte-identical decode-step jaxprs;
    - the armed streams parse and carry schema-v4 ``slo`` events;
    - the burn-rate semantics hold on synthetic streams: a sustained
      p99 breach trips the fast+slow alert, a single transient spike
      trips nothing."""
    import shutil
    import tempfile
    import numpy as np
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.monitor import (Event, Monitor, SLOConfig,
                                       SLOEvaluator, parse_line)
    from deepspeed_tpu.monitor.sinks import EVENTS_FILE
    from .findings import Finding
    from .jaxpr_audit import audit_engine, train_step_jaxpr_text

    findings = []

    # ---- synthetic burn-rate semantics (pure host math) --------------
    cfg = SLOConfig.from_value({
        "objectives": [{"name": "p99", "series": "latency_p99_ms",
                        "max": 500.0, "target": 0.99}],
        "fast_window": 10, "slow_window": 100,
        "fast_burn": 10.0, "slow_burn": 10.0, "sentinel": False})

    def drive(values):
        ev = SLOEvaluator(cfg)
        alerts = []
        for i, v in enumerate(values):
            for e in ev.feed(Event(kind="gauge", name="latency_p99_ms",
                                   t=float(i), step=i, value=v)):
                if e.kind == "alert" and e.fields.get("state") == "trip":
                    alerts.append(i)
        return alerts

    sustained = drive([100.0] * 50 + [900.0] * 50)
    if not sustained:
        findings.append(Finding(
            "DSTPU104", "error",
            "--audit-step slo: a sustained p99 breach did not trip the "
            "fast+slow burn-rate alert", eqn_path="slo/burn-rate"))
    transient = drive([100.0] * 50 + [900.0] + [100.0] * 100)
    if transient:
        findings.append(Finding(
            "DSTPU104", "error",
            f"--audit-step slo: a single transient spike PAGED (trips at "
            f"observations {transient}) — the slow window must absorb it",
            eqn_path="slo/burn-rate"))

    # ---- train twin --------------------------------------------------
    data = (np.ones((8, 16), np.float32), np.ones((8, 16), np.float32))
    dataset = [(data[0][i], data[1][i]) for i in range(8)]
    mon_dir = tempfile.mkdtemp(prefix="dstpu-audit-slo-")

    def build(mon_cfg):
        cfg = {"train_micro_batch_size_per_gpu": 4,
               "gradient_accumulation_steps": 1,
               "steps_per_print": 10 ** 9,
               "bf16": {"enabled": True},
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 2},
               "compile_cache": {"dir": cache_dir}}
        if mon_cfg:
            cfg["monitor"] = mon_cfg
        return ds.initialize(config=cfg, model=_MLP(),
                             training_data=dataset)[0]

    slo_block = {"objectives": [
        {"name": "throughput", "series": "tokens_per_sec", "min": 1e-9},
        {"name": "mfu_floor", "series": "mfu", "min": 1e-12,
         "target": 0.9}]}

    def read_kinds(run_dir, what):
        try:
            with open(os.path.join(run_dir, EVENTS_FILE)) as fh:
                return {parse_line(ln).kind for ln in fh if ln.strip()}
        except (OSError, ValueError) as e:
            findings.append(Finding(
                "DSTPU104", "error",
                f"--audit-step slo: {what} event stream did not parse "
                f"({e})", eqn_path="slo/stream"))
            return None

    try:
        off = build(None)
        armed = build({"enabled": True, "dir": mon_dir,
                       "sinks": ["jsonl"], "interval": 1,
                       "slo": slo_block})
        if train_step_jaxpr_text(off) != train_step_jaxpr_text(armed):
            findings.append(Finding(
                "DSTPU201", "error",
                "--audit-step slo: arming the SLO engine CHANGED the "
                "traced train step (jaxpr slo-on != slo-off) — "
                "objective evaluation leaked into the compiled program",
                eqn_path="slo/jaxpr-equality"))
        off.close()
        armed.train_batch()
        armed.train_batch()
        report = audit_engine(armed)
        for f in report.findings:
            f.extra = dict(f.extra, audit="slo-armed")
        findings.extend(report.findings)
        armed.close()             # terminal flush emits the slo verdicts
        kinds = read_kinds(mon_dir, "train")
        if kinds is not None and "slo" not in kinds:
            findings.append(Finding(
                "DSTPU104", "error",
                f"--audit-step slo: the armed train run emitted no `slo` "
                f"events (got {sorted(kinds)})", eqn_path="slo/stream"))
    finally:
        shutil.rmtree(mon_dir, ignore_errors=True)

    # ---- serving twin ------------------------------------------------
    from deepspeed_tpu.models.gpt2 import GPT2, GPT2Config
    from deepspeed_tpu.inference import (ServingEngine, ServingConfig,
                                         Request)
    gcfg = GPT2Config(vocab_size=64, max_seq=32, n_embd=32, n_layer=2,
                      n_head=4, embd_pdrop=0.0, attn_pdrop=0.0,
                      resid_pdrop=0.0, attention_impl="jnp")
    model = GPT2(gcfg, dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0))
    scfg = dict(batch_slots=2, block_size=8, max_new_tokens=4,
                preflight=False)

    def decode_jaxpr(srv):
        srv._build_decode()
        return str(jax.make_jaxpr(srv._decode)(*srv._decode_args()))

    clean = ServingEngine(model=model, params=params,
                          config=ServingConfig(**scfg))
    clean_jaxpr = decode_jaxpr(clean)
    clean.close()
    run_dir = tempfile.mkdtemp(prefix="dstpu-audit-slo-srv-")
    try:
        mon = Monitor(run_dir=run_dir, role="serving",
                      slo={"objectives": [
                          {"name": "p99", "series": "latency_p99_ms",
                           "max": 1e9},
                          {"name": "errors", "series": "error_rate",
                           "max": 0.5}]})
        armed = ServingEngine(model=model, params=params, monitor=mon,
                              config=ServingConfig(**scfg))
        if decode_jaxpr(armed) != clean_jaxpr:
            findings.append(Finding(
                "DSTPU201", "error",
                "--audit-step slo: arming the monitor+SLO engine "
                "CHANGED the traced decode step (jaxpr armed != "
                "disarmed)", eqn_path="slo/jaxpr-equality"))
        armed.run([Request(tokens=np.arange(4), max_new_tokens=8,
                           uid=u) for u in range(2)])
        verdict = armed.slo_report()
        if not verdict or verdict.get("objectives_total") != 2:
            findings.append(Finding(
                "DSTPU104", "error",
                f"--audit-step slo: ServingEngine.slo_report() did not "
                f"carry the armed objectives (got {verdict})",
                eqn_path="slo/report"))
        armed.close()
        mon.close()
        kinds = read_kinds(run_dir, "serving")
        if kinds is not None and "slo" not in kinds:
            findings.append(Finding(
                "DSTPU104", "error",
                f"--audit-step slo: the armed serving run emitted no "
                f"`slo` events (got {sorted(kinds)})",
                eqn_path="slo/stream"))
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)
    return findings


def _audit_elastic_resume():
    """--audit-step elastic: audit the FIRST compiled step after an elastic
    reshard-on-resize (docs/elasticity.md) — a ZeRO-2 elastic engine saves
    on the full device set, a second engine auto-resumes on HALF of it, and
    the resumed engine's train step must show zero host callbacks
    (DSTPU201) and every declared donation honored on the NEW mesh
    (DSTPU204)."""
    import shutil
    import tempfile
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.parallel.mesh import make_mesh
    from .findings import Finding
    from .jaxpr_audit import audit_engine

    # both n and n//2 must be schedulable by the fixed elastic block below
    # (micro [2,4], max 16 -> valid world sizes {1,2,4,8})
    n = jax.device_count()
    if n not in (2, 4, 8):
        return [Finding(
            "DSTPU200", "warning",
            f"--audit-step elastic needs a device count in (2,4,8) so the "
            f"built-in elastic schedule covers both the full and the "
            f"halved mesh (got {n}); skipped",
            eqn_path="elastic-resume")]

    import numpy as np
    data = (np.ones((32, 16), np.float32), np.ones((32, 16), np.float32))
    dataset = [(data[0][i], data[1][i]) for i in range(32)]
    cfg = {"steps_per_print": 10 ** 9,
           "bf16": {"enabled": True},
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 2},
           "elasticity": {"enabled": True, "max_train_batch_size": 16,
                          "micro_batch_sizes": [2, 4], "min_gpus": 1,
                          "max_gpus": 64, "version": 0.1}}
    findings = []
    ckpt_dir = tempfile.mkdtemp(prefix="dstpu-audit-elastic-")
    try:
        a, _, _, _ = ds.initialize(config=dict(cfg), model=_MLP(),
                                   training_data=dataset,
                                   mesh=make_mesh({"data": n}))
        a.train_batch()
        a.save_checkpoint(ckpt_dir)
        a.close()

        half = n // 2
        cfg_b = dict(cfg, checkpoint={"dir": ckpt_dir, "auto_resume": True})
        b, _, _, _ = ds.initialize(
            config=cfg_b, model=_MLP(), training_data=dataset,
            mesh=make_mesh({"data": half}, devices=jax.devices()[:half]))
        if b.global_steps != 1:
            findings.append(Finding(
                "DSTPU200", "warning",
                f"--audit-step elastic: resume on {half} devices did not "
                f"restore the checkpointed step (global_steps="
                f"{b.global_steps})", eqn_path="elastic-resume"))
        report = audit_engine(b)
        for f in report.findings:
            f.extra = dict(f.extra, audit="elastic-resume",
                           from_world=n, to_world=half)
        findings.extend(report.findings)
        b.close()
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    return findings


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis",
        description="jaxpr auditor + tracing-safety lint")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the deepspeed_tpu "
                         "package)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output on stdout")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--strict", action="store_true",
                    help="warnings also fail the run")
    ap.add_argument("--audit-step", default=None, metavar="STAGES",
                    help="also jaxpr-audit built-in tiny engines, e.g. "
                         "--audit-step 1,2,3 (compiles; needs jax). A "
                         "'q' suffix (e.g. 3q) audits the quantized-"
                         "collectives variant and additionally gates the "
                         "census against the engine's declared CommsBudget; "
                         "'decode' audits the serving layer's fused paged "
                         "decode step + generate()'s fused token scan; "
                         "'serving-resilience' audits the quarantine-"
                         "sentinel-armed serving step (zero host "
                         "callbacks, donation honored, logit_nan fault "
                         "jaxpr-identical; docs/serving.md#resilience); "
                         "'serving-lifecycle' proves the lifecycle "
                         "layers: shadow-sanitizer armed vs off jaxpr "
                         "AND token identity, every DSTPU31x violation "
                         "class caught on seeded violations, and the "
                         "full 720-ordering crash-handoff interleaving "
                         "sweep reports zero lost/duplicated uids "
                         "(docs/static-analysis.md#lifecycle); "
                         "'paged-attn' audits the in-place paged-"
                         "attention kernel decode step (zero host "
                         "callbacks, pool donation honored, NO gathered "
                         "K/V materialization in the jaxpr — census "
                         "sanity-checked against the gather fallback) "
                         "and speculative-decode armed-vs-disarmed "
                         "token equivalence (docs/serving.md); "
                         "'elastic' audits the first resharded step after "
                         "an elastic resume on half the devices "
                         "(docs/elasticity.md); 'moe' audits the quantized "
                         "expert-parallel dispatch on a data×expert mesh "
                         "(int8 on the wire, two-level replica groups, "
                         "tight budget); 'monitor' proves an ARMED "
                         "telemetry monitor leaves the compiled step "
                         "byte-identical and host-callback-free while "
                         "its JSONL stream parses (docs/monitoring.md); "
                         "'tracing' proves request-scoped tracing at "
                         "trace_sample_rate=1.0 leaves the serving "
                         "decode step jaxpr-identical (zero host "
                         "callbacks, donation honored) while emitting "
                         "parseable trace events with monotone spans "
                         "(docs/monitoring.md#request-tracing); 'mem' "
                         "proves the memory ledger leaves BOTH the "
                         "compiled train step and the serving decode "
                         "step byte-identical ledger-on vs off while "
                         "its schema-v3 `mem` events parse and name "
                         "the expected subsystems "
                         "(docs/monitoring.md#memory-explainability); "
                         "'slo' proves the SLO engine leaves BOTH "
                         "compiled steps byte-identical armed vs off, "
                         "emits parseable schema-v4 `slo` events, and "
                         "honors the multi-window burn-rate semantics "
                         "on synthetic streams (sustained breach trips, "
                         "transient spike does not; "
                         "docs/monitoring.md#slo-tracking)")
    args = ap.parse_args(argv)

    # findings are the stdout payload (the tier-1 gate parses --json);
    # engine/mesh INFO chatter must not interleave
    from ..utils.logging import route_logs_to_stderr
    route_logs_to_stderr()

    rules = select_rules(args.rules.split(",") if args.rules else None)
    if args.list_rules:
        for rule in sorted(rules, key=lambda r: r.id):
            print(f"{rule.id}  {rule.name:28s} [{rule.severity}] "
                  f"{rule.description}")
        return 0

    paths = args.paths or _default_paths()
    root = os.getcwd()
    findings = lint_paths(paths, rules=rules, root=root)
    if args.audit_step:
        stages = [s.strip() for s in args.audit_step.split(",")]
        findings.extend(_audit_builtin_steps(stages))

    counts = counts_by_severity(findings)
    failing = counts["error"] + (counts["warning"] if args.strict else 0)
    if args.as_json:
        print(json.dumps({"version": 1,
                          "rules": sorted(r.id for r in rules),
                          "findings": [f.to_dict() for f in findings],
                          "counts": counts,
                          "ok": failing == 0}))
    else:
        for f in findings:
            print(str(f))
        total = len(findings)
        print(f"{total} finding(s): " +
              ", ".join(f"{counts[s]} {s}" for s in ("error", "warning",
                                                     "info")))
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
