"""Roofline attribution: explain measured step time against the chip.

``ds_explain`` (``python -m deepspeed_tpu.analysis.roofline <run_dir>``,
or ``bin/ds_explain``) turns a monitor event stream into a per-executable
*verdict*: which roofline the step is actually hitting — **compute**
(peak FLOPS), **HBM** (memory bandwidth), or **wire** (interconnect) —
what fraction of that binding roofline the measured wall achieves, and a
decomposition of the gap:

- modeled device time  = max(flops/peak, hbm_bytes/bw, wire_bytes/ici)
  (the roofline model: terms overlap; the largest one binds);
- host/scheduling time = measured wall − modeled device time (dispatch
  gaps, host work, Python — everything the chip was NOT the reason for);
- gather-materialization bytes: the paged decode path materializes each
  slot's gathered K/V blocks before attending (``paged_kv.gather_kv``'s
  honest cost note) — those bytes are named explicitly as a slice of
  the HBM term, because they are the exact traffic the ROADMAP-1
  in-place Pallas kernel deletes.

Inputs, all already on the bus (docs/monitoring.md#ds_explain):

- ``exe_cost`` gauge events — one per priced executable: XLA
  cost-analysis FLOPs + ``bytes accessed``, the HLO wire census bytes
  (``analysis/comms.py``), the producing device kind and chip count;
- ``step`` events (``fields.wall_s``) and/or the ``step_wall_ms`` hist
  event — the measured wall-time stream;
- the shared :data:`monitor.gauges.CHIP_TABLE` (peak FLOPS + HBM +
  ICI bandwidth per generation; ``--chip``/``--hbm-gb-s``/... override).

This makes ROADMAP item 1's hand-argued "b8 decode at 0.48 of the HBM
bound" (INFERENCE_BENCH.json) a regenerable report: the acceptance test
replays that bench's numbers through this module and reproduces the
fraction (tests/test_roofline.py).
"""

import argparse
import json
import os
import sys

from ..monitor.gauges import CHIP_TABLE, chip_specs

BOUNDS = ("compute", "hbm", "wire")

# warmup steps excluded from the wall-time series (compile/deserialize)
DEFAULT_WARMUP_STEPS = 2


def gather_materialization_bytes(*, n_layer, batch_slots, nb_max,
                                 block_size, n_head, head_dim,
                                 itemsize, paged_impl="gather",
                                 n_window=1) -> int:
    """HBM traffic of the paged decode's gather materialization, per
    decode step — FOR THE LIVE IMPLEMENTATION.

    The legacy/fallback ``paged_impl="gather"`` path
    (``paged_kv.gather_kv``) gathers every slot's K AND V block lists
    into dense ``(B, nb_max·block_size, H, hd)`` copies per layer,
    written once and read once — 4x the slot's KV bytes of traffic.
    The in-place Pallas kernel (``paged_impl="kernel"``,
    ``ops/transformer/paged_attention.py``) DMAs blocks straight from
    the pool: the term is **0**, and ``ds_explain`` proves the bytes
    are gone rather than keeping a modeled cost the implementation no
    longer pays.  ``n_window`` scales the window width (speculative
    scoring steps gather once per step regardless of window, so the
    term is window-independent; kept explicit for clarity)."""
    if paged_impl == "kernel":
        return 0
    assert paged_impl == "gather", f"unknown paged_impl {paged_impl!r}"
    del n_window                             # gather is per step, not per row
    copy = 2 * n_layer * batch_slots * nb_max * block_size \
        * n_head * head_dim * itemsize       # K + V materialized copies
    return 2 * copy                          # written, then read


def attribute(*, wall_s, flops=0, hbm_bytes=0, wire_bytes=0,
              chip=None, n_chips=1, gather_bytes=0,
              paged_impl=None) -> dict:
    """One executable's roofline verdict (module docstring).

    ``chip`` is a :func:`monitor.gauges.chip_specs` row (default: the
    local device's).  Returns bound / achieved_frac / per-term modeled
    times / the gap decomposition; ``achieved_frac`` is modeled-bound
    time over measured wall, i.e. 1.0 = running AT the binding roofline.
    """
    if wall_s is None or wall_s <= 0:
        raise ValueError(f"wall_s must be > 0, got {wall_s}")
    chip = dict(chip) if chip else chip_specs()
    n_chips = max(1, int(n_chips))
    t_compute = flops / (chip["peak_bf16_flops"] * n_chips) if flops else 0.0
    t_hbm = (hbm_bytes / (chip["hbm_gb_s"] * 1e9 * n_chips)
             if hbm_bytes else 0.0)
    t_wire = (wire_bytes / (chip["ici_gb_s"] * 1e9 * n_chips)
              if wire_bytes else 0.0)
    terms = {"compute": t_compute, "hbm": t_hbm, "wire": t_wire}
    bound = max(terms, key=terms.get)
    t_bound = terms[bound]
    if t_bound <= 0:
        bound = "unknown"
    achieved = (t_bound / wall_s) if t_bound > 0 else None
    gap_s = max(0.0, wall_s - t_bound)
    out = {
        "bound": bound,
        "achieved_frac": round(achieved, 4) if achieved is not None
        else None,
        "wall_s": wall_s,
        "modeled": {k: round(v, 12) for k, v in terms.items()},
        "modeled_device_s": round(t_bound, 12),
        "gap": {
            "host_scheduling_s": round(gap_s, 12),
            "host_pct": round(100.0 * gap_s / wall_s, 2),
        },
        "inputs": {"flops": int(flops), "hbm_bytes": int(hbm_bytes),
                   "wire_bytes": int(wire_bytes), "n_chips": n_chips},
        "chip": {k: chip.get(k) for k in
                 ("device_kind", "matched", "peak_bf16_flops",
                  "hbm_gb_s", "ici_gb_s", "nominal") if k in chip},
    }
    if paged_impl is not None:
        # which paged-attention impl produced this stream: the verdict
        # names it so "the gather bytes are gone" is a reported fact,
        # not an inference (kernel → the term below is exactly 0)
        out["paged_attention_impl"] = str(paged_impl)
    if gather_bytes or paged_impl is not None:
        # named explicitly: the slice of the HBM term the in-place
        # paged-attention kernel recovers (0 when the kernel IS the
        # live impl — the ROADMAP-1 acceptance evidence)
        g_s = gather_bytes / (chip["hbm_gb_s"] * 1e9 * n_chips)
        out["gap"]["gather_materialization_bytes"] = int(gather_bytes)
        out["gap"]["gather_materialization_s"] = round(g_s, 12)
        if hbm_bytes:
            out["gap"]["gather_pct_of_hbm_bytes"] = round(
                100.0 * gather_bytes / hbm_bytes, 2)
    return out


# --------------------------------------------------------------- the stream

def _median(vals):
    if not vals:
        return None
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def fold_stream(events, warmup=DEFAULT_WARMUP_STEPS) -> dict:
    """Collect what the verdicts need from a parsed event stream:
    per-step-name wall series (warmup-trimmed), the newest ``exe_cost``
    record per executable, and the newest ``step_wall_ms`` histogram."""
    walls = {}                   # step name -> [wall_s, ...]
    costs = {}                   # exe name  -> exe_cost fields
    step_hist = None
    for e in events:
        if e.kind == "step" and e.fields.get("wall_s"):
            walls.setdefault(e.name, []).append(float(e.fields["wall_s"]))
        elif e.kind == "gauge" and e.name == "exe_cost":
            exe = e.fields.get("exe")
            if exe:
                costs[str(exe)] = dict(e.fields)
        elif e.kind == "hist" and e.name == "step_wall_ms":
            step_hist = e.fields
    walls = {k: (v[warmup:] if len(v) > warmup else v)
             for k, v in walls.items()}
    return {"walls": walls, "costs": costs, "step_wall_hist": step_hist}


def explain(folded, *, chip=None) -> dict:
    """Per-executable verdicts from a :func:`fold_stream` result (which
    already applied the warmup trim).  The wall estimate is the p50 of
    the ``step_wall_ms`` histogram when the stream carries one
    (whole-run, exact-count), else the median of the step events'
    ``wall_s`` series (interval-thinned)."""
    verdicts = {}
    for exe, cost in folded["costs"].items():
        wall_s = None
        wall_src = None
        if exe == "serving_step" and folded["step_wall_hist"]:
            from ..monitor.histogram import LogHistogram
            try:
                h = LogHistogram.from_dict(folded["step_wall_hist"])
                if h:
                    wall_s = h.quantile(0.5) / 1e3
                    wall_src = f"step_wall_ms hist p50 (n={h.count})"
            except (KeyError, TypeError, ValueError):
                pass
        if wall_s is None:
            series = folded["walls"].get(exe) or []
            wall_s = _median(series)
            wall_src = f"median of {len(series)} step wall_s samples"
        if not wall_s:
            verdicts[exe] = {"error": "no measured wall time in the "
                             "stream for this executable"}
            continue
        row = chip or (chip_specs(cost.get("device_kind"))
                       if cost.get("device_kind") else None)
        v = attribute(
            wall_s=wall_s,
            flops=cost.get("flops") or 0,
            hbm_bytes=cost.get("hbm_bytes") or 0,
            wire_bytes=cost.get("wire_bytes") or 0,
            chip=row, n_chips=cost.get("n_chips") or 1,
            gather_bytes=cost.get("gather_bytes") or 0,
            paged_impl=cost.get("paged_impl"))
        v["wall_source"] = wall_src
        if cost.get("tokens_per_step"):
            v["tokens_per_step"] = cost["tokens_per_step"]
        verdicts[exe] = v
    return verdicts


# ----------------------------------------------------------------- the CLI

def _fmt_ms(s):
    return f"{s * 1e3:.3f} ms"


def render(verdicts: dict, source: str) -> str:
    lines = [f"ds_explain — roofline attribution over {source}", ""]
    if not verdicts:
        lines.append(
            "no priced executables in the stream (no `exe_cost` events) "
            "— run with the monitor enabled on a build that emits them "
            "(docs/monitoring.md#ds_explain)")
        return "\n".join(lines)
    for exe, v in sorted(verdicts.items()):
        if "error" in v:
            lines.append(f"[{exe}] {v['error']}")
            continue
        c = v["chip"]
        nom = " (NOMINAL table row — non-TPU backend)" if c.get("nominal") \
            else ""
        lines += [
            f"[{exe}]  wall {_fmt_ms(v['wall_s'])} "
            f"({v['wall_source']})",
            f"  chip: {c.get('device_kind')} -> {c.get('matched')}{nom}: "
            f"{c['peak_bf16_flops'] / 1e12:.0f} TFLOPs, "
            f"HBM {c['hbm_gb_s']:.0f} GB/s, ICI {c['ici_gb_s']:.0f} GB/s "
            f"x{v['inputs']['n_chips']} chip(s)",
            f"  modeled: compute {_fmt_ms(v['modeled']['compute'])} | "
            f"HBM {_fmt_ms(v['modeled']['hbm'])} | "
            f"wire {_fmt_ms(v['modeled']['wire'])}",
        ]
        if v["achieved_frac"] is not None:
            lines.append(
                f"  verdict: {v['bound'].upper()}-BOUND — achieved "
                f"{v['achieved_frac']:.2f} of the {v['bound']} roofline")
        else:
            lines.append("  verdict: UNKNOWN — no cost inputs priced")
        g = v["gap"]
        lines.append(
            f"  gap: host/scheduling {_fmt_ms(g['host_scheduling_s'])} "
            f"({g['host_pct']:.0f}% of wall)")
        if "gather_materialization_bytes" in g:
            impl = v.get("paged_attention_impl")
            if impl == "kernel" and not g["gather_materialization_bytes"]:
                lines.append(
                    "    paged attention: in-place Pallas kernel — "
                    "gather materialization 0 B/step (the copy the "
                    "gather fallback would pay is deleted)")
            else:
                tag = f" [impl: {impl}]" if impl else ""
                lines.append(
                    f"    gather materialization (paged decode{tag}): "
                    f"{g['gather_materialization_bytes'] / 1e6:.1f} MB/step "
                    f"= {_fmt_ms(g['gather_materialization_s'])} of the HBM "
                    f"term ({g.get('gather_pct_of_hbm_bytes', 0):.1f}% of "
                    f"HBM bytes) — the in-place kernel "
                    f"(paged_attention_impl=kernel) deletes it")
        lines.append("")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds_explain",
        description="roofline attribution over a monitor event stream "
                    "(docs/monitoring.md#ds_explain)")
    ap.add_argument("run", help="monitor run dir (or an events.jsonl path)")
    ap.add_argument("--chip", default=None,
                    help=f"chip table row to price against (default: the "
                         f"stream's device_kind); one of "
                         f"{sorted(CHIP_TABLE)}")
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="override peak bf16 TFLOPs per chip")
    ap.add_argument("--hbm-gb-s", type=float, default=None,
                    help="override HBM GB/s per chip")
    ap.add_argument("--ici-gb-s", type=float, default=None,
                    help="override interconnect GB/s per chip")
    ap.add_argument("--warmup", type=int, default=DEFAULT_WARMUP_STEPS,
                    help="leading steps to drop from the wall series "
                         f"(default {DEFAULT_WARMUP_STEPS})")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdicts as JSON instead of the report")
    args = ap.parse_args(argv)

    from ..monitor.__main__ import StreamFollower, resolve_stream
    stream = resolve_stream(args.run)
    if not os.path.exists(stream):
        print(f"ds_explain: no event stream at {stream}", file=sys.stderr)
        return 1
    events = StreamFollower(stream).poll()
    folded = fold_stream(events, warmup=args.warmup)

    chip = None
    if args.chip:
        if args.chip not in CHIP_TABLE:
            print(f"ds_explain: unknown --chip {args.chip!r}; known: "
                  f"{sorted(CHIP_TABLE)}", file=sys.stderr)
            return 2
        chip = dict(CHIP_TABLE[args.chip], device_kind=args.chip,
                    matched=args.chip)
    if args.peak_tflops or args.hbm_gb_s or args.ici_gb_s:
        chip = dict(chip or chip_specs())
        if args.peak_tflops:
            chip["peak_bf16_flops"] = args.peak_tflops * 1e12
        if args.hbm_gb_s:
            chip["hbm_gb_s"] = args.hbm_gb_s
        if args.ici_gb_s:
            chip["ici_gb_s"] = args.ici_gb_s

    verdicts = explain(folded, chip=chip)
    if args.json:
        print(json.dumps(verdicts, indent=2, sort_keys=True))
    else:
        print(render(verdicts, stream))
    return 0


if __name__ == "__main__":
    sys.exit(main())
