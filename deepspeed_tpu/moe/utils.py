"""MoE parameter bookkeeping.

Parity: reference ``deepspeed/moe/utils.py`` — ``is_moe_param`` /
``split_params_into_different_moe_groups_for_optimizer``.  The reference tags
torch Parameters with ``.allreduce=False`` and group names so ZeRO can build
expert-aware partitions (``stage_1_and_2.py:519 _configure_moe_settings``).
Here params live in pytrees: MoE membership is a *path* property (any path
segment named ``experts``), and "MoE-aware partitioning" is simply the
``expert`` axis appearing in the leaf's PartitionSpec.
"""

import jax


def _path_names(path):
    names = []
    for k in path:
        if hasattr(k, "key"):
            names.append(str(k.key))
        elif hasattr(k, "name"):
            names.append(str(k.name))
        elif hasattr(k, "idx"):
            names.append(str(k.idx))
        else:
            names.append(str(k))
    return names


def is_moe_param_path(path) -> bool:
    """True when a pytree path addresses an expert-parallel parameter."""
    return "experts" in _path_names(path)


def split_moe_params(params):
    """Split a param pytree into (non_moe, moe) trees with ``None`` holes.

    Role parity: reference ``split_params_into_different_moe_groups_for_optimizer``
    building separate optimizer param groups for expert vs dense params.
    """
    non_moe = jax.tree_util.tree_map_with_path(
        lambda p, x: None if is_moe_param_path(p) else x, params)
    moe = jax.tree_util.tree_map_with_path(
        lambda p, x: x if is_moe_param_path(p) else None, params)
    return non_moe, moe
