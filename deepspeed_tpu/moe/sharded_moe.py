"""GShard-style top-k gating + SPMD dispatch, TPU-native.

Behavior parity: reference ``deepspeed/moe/sharded_moe.py`` —
``top1gating`` (:172), ``top2gating`` (:278), ``TopKGate`` (:353),
``MOELayer`` (:443) with its ``_AllToAll`` autograd op (:85).

TPU re-design notes (NOT a port):

- Everything is functional jnp with explicit RNG; the gate math runs in fp32
  exactly like the reference (``TopKGate.forward`` casts, :399-441).
- **Static capacity**: XLA requires static shapes, so the expert capacity is
  computed at trace time from the (static) token count:
  ``capacity = max(ceil(tokens/experts × capacity_factor), min_capacity)``
  (reference ``_capacity``, :149-160).  The reference's ``drop_tokens=False``
  mode discovers the needed capacity at runtime with an allreduce-MAX
  (:213-217); here no-drop defaults to the GUARANTEED worst case
  (capacity = token count) so nothing is ever dropped, honoring the
  reference contract at the cost of an S×E×S dispatch.  Pass
  ``max_capacity=<bound>`` to opt into bounded memory instead — overflow
  is then detectable via ``tokens_overflowed(exp_counts, capacity)``
  (``MoE.apply(..., return_overflow=True)`` surfaces the count).
- **Dispatch/combine are einsums** on a one-hot routing tensor, and expert
  parallelism is a *sharding* of the expert dimension over the ``expert`` mesh
  axis — the SPMD partitioner inserts the all-to-alls the reference wrote by
  hand; ``jax.lax`` einsum contractions are differentiable so the custom
  autograd Function disappears.
- Random Token Selection (:225-237) keeps tokens by random priority instead of
  sequence order when over capacity; implemented with the same top-capacity
  selection over a noise-scaled mask.
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp


def compute_capacity(num_tokens: int, num_experts: int, capacity_factor: float,
                     min_capacity: int) -> int:
    """Static capacity (reference ``_capacity``, ``sharded_moe.py:149-160``)."""
    capacity = int(math.ceil((num_tokens / num_experts) * capacity_factor))
    return max(capacity, int(min_capacity))


def _keep_topc_per_expert(priority, mask, capacity: int):
    """Keep at most ``capacity`` tokens per expert, highest ``priority`` first.

    priority, mask: (S, E).  Returns the thinned mask.
    Implements the scatter-by-top-idx of the reference (:239-244) with a
    static-shape ``top_k`` over the token axis.
    """
    num_tokens = mask.shape[0]
    c = min(capacity, num_tokens)
    # (E, S) → indices of the top-c tokens per expert
    _, top_idx = jax.lax.top_k(priority.T, c)              # (E, c)
    keep = jax.nn.one_hot(top_idx, num_tokens, dtype=mask.dtype)  # (E, c, S)
    keep = keep.sum(axis=1).T                               # (S, E)
    return mask * keep


def nodrop_capacity(num_tokens: int, num_experts: int,
                    max_capacity: Optional[int], min_capacity: int) -> int:
    """Static capacity for ``drop_tokens=False`` gating.

    DEFAULT = ``num_tokens``: the guaranteed worst case, honoring the
    reference's no-drop contract (``sharded_moe.py:213-217`` sizes it at
    runtime with an allreduce-MAX over actual load — impossible under
    XLA's static shapes, so the static worst case is the only
    drop-free choice).  The cost is an S×E×S dispatch mask; a model
    that wants bounded memory instead opts IN to a cap with
    ``max_capacity`` and monitors ``tokens_overflowed``."""
    if max_capacity is not None:
        # the user's explicit memory bound WINS (min_capacity must not
        # silently exceed it); clamp to num_tokens — capacity beyond the
        # token count buys nothing
        return min(num_tokens, max(1, int(max_capacity)))
    return num_tokens


def tokens_overflowed(exp_counts, capacity: int):
    """Tokens dropped by capacity thinning, from the PRE-thinning demand
    counts the gates return: ``sum_e max(0, exp_counts[e] - capacity)``.
    Exact for top-1 gating; an upper bound for top-2 (second-choice
    assignments may be dropped without losing the token entirely)."""
    return jnp.sum(jnp.maximum(exp_counts - capacity, 0))


def top1gating(logits, capacity_factor: float, min_capacity: int,
               *, rng=None, used_token=None,
               noisy_gate_policy: Optional[str] = None,
               drop_tokens: bool = True, use_rts: bool = True,
               max_capacity: Optional[int] = None):
    """Top-1 gating (reference ``sharded_moe.py:172-275``).

    logits: (S, E) fp32.  Returns ``(l_aux, combine_weights (S,E,C),
    dispatch_mask (S,E,C) bool, exp_counts (E,))``.

    ``drop_tokens=False``: capacity defaults to the GUARANTEED no-drop
    worst case (= token count, the static equivalent of the reference's
    runtime max-allreduce, :213-217).  An explicit ``max_capacity``
    opts into a bounded S×E×C dispatch; demand beyond that cap IS
    dropped (lowest-priority first) — detect it with
    ``tokens_overflowed(exp_counts, capacity)``, where ``exp_counts``
    is the pre-thinning demand, so the overflow count is exact.
    """
    (l_aux, indices1_s, locations1_s, gates1_s, kept,
     exp_counts, capacity) = top1_routes(
        logits, capacity_factor, min_capacity, rng=rng,
        used_token=used_token, noisy_gate_policy=noisy_gate_policy,
        drop_tokens=drop_tokens, use_rts=use_rts, max_capacity=max_capacity)
    num_experts = logits.shape[1]
    se = jax.nn.one_hot(indices1_s, num_experts,
                        dtype=jnp.float32) * gates1_s[:, None]
    locations1_sc = jax.nn.one_hot(locations1_s, capacity, dtype=jnp.float32)
    combine_weights = jnp.einsum("se,sc->sec", se, locations1_sc)
    dispatch_mask = combine_weights.astype(bool)
    return l_aux, combine_weights, dispatch_mask, exp_counts


def top1_routes(logits, capacity_factor: float, min_capacity: int,
                *, rng=None, used_token=None,
                noisy_gate_policy: Optional[str] = None,
                drop_tokens: bool = True, use_rts: bool = True,
                max_capacity: Optional[int] = None):
    """The routing core of ``top1gating`` in COMPACT form — per token its
    expert, capacity slot, and gate weight (0 if dropped) — for the
    scatter/gather dispatch (``MOELayer(dispatch_impl="scatter")``) that
    replaces the S×E×C one-hot einsum.

    Returns ``(l_aux, indices (S,), locations (S,), gate_weights (S,),
    kept (S,) bool, exp_counts (E,), capacity)``."""
    logits = logits.astype(jnp.float32)
    num_tokens, num_experts = logits.shape

    if noisy_gate_policy == "RSample":
        assert rng is not None, "RSample noisy gating needs rng"
        rng, sub = jax.random.split(rng)
        logits_w_noise = logits + jax.random.gumbel(sub, logits.shape, jnp.float32)
    else:
        logits_w_noise = logits

    gates = jax.nn.softmax(logits, axis=1)

    if drop_tokens:
        capacity = compute_capacity(num_tokens, num_experts, capacity_factor,
                                    min_capacity)
    else:
        capacity = nodrop_capacity(num_tokens, num_experts, max_capacity,
                                   min_capacity)

    indices1_s = jnp.argmax(logits_w_noise if noisy_gate_policy == "RSample"
                            else gates, axis=1)
    mask1 = jax.nn.one_hot(indices1_s, num_experts, dtype=jnp.int32)
    if used_token is not None:
        mask1 = mask1 * used_token[:, None].astype(mask1.dtype)

    exp_counts = mask1.sum(axis=0)

    # aux load-balancing loss (reference :220-222)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(jnp.float32), axis=0)
    l_aux = jnp.sum(me * ce) * num_experts

    # capacity thinning: random (RTS) or sequence priority (reference :225-244)
    if use_rts:
        assert rng is not None, "Random Token Selection needs rng"
        rng, sub = jax.random.split(rng)
        priority = mask1 * jax.random.uniform(sub, mask1.shape, jnp.float32)
    else:
        # earlier tokens win: priority decreasing with position
        pos = jnp.arange(num_tokens, dtype=jnp.float32)[:, None]
        priority = mask1 * (num_tokens - pos)
    mask1 = _keep_topc_per_expert(priority, mask1, capacity)

    # position of each kept token inside its expert's capacity buffer
    locations1 = jnp.cumsum(mask1, axis=0) - 1
    # RTS can keep a token whose cumsum position exceeds capacity; re-drop
    mask1 = mask1 * (locations1 < capacity).astype(mask1.dtype)
    locations1_s = jnp.sum(locations1 * mask1, axis=1)

    kept = mask1.sum(axis=1) > 0
    gates1_s = jnp.sum(gates * mask1.astype(jnp.float32), axis=1)
    return (l_aux, indices1_s, locations1_s, gates1_s, kept,
            exp_counts, capacity)


def top2gating(logits, capacity_factor: float, min_capacity: int, *, rng=None):
    """Top-2 gating (reference ``sharded_moe.py:278-351``): second expert via
    the Gumbel-max trick, combine weights normalized over the two experts."""
    (l_aux, routes, exp_counts, capacity) = top2_routes(
        logits, capacity_factor, min_capacity, rng=rng)
    num_experts = logits.shape[1]
    combine_weights = 0.0
    for idx, loc, w in routes:
        se = jax.nn.one_hot(idx, num_experts, dtype=jnp.float32) * w[:, None]
        sc = jax.nn.one_hot(loc, capacity, dtype=jnp.float32)
        combine_weights = combine_weights + jnp.einsum("se,sc->sec", se, sc)
    dispatch_mask = combine_weights.astype(bool)
    return l_aux, combine_weights, dispatch_mask, exp_counts


def top2_routes(logits, capacity_factor: float, min_capacity: int, *,
                rng=None):
    """Routing core of ``top2gating`` in compact form: returns
    ``(l_aux, [(idx, loc, weight)] x2, exp_counts, capacity)`` where dropped
    routes carry weight 0."""
    logits = logits.astype(jnp.float32)
    num_tokens, num_experts = logits.shape
    gates = jax.nn.softmax(logits, axis=1)
    capacity = compute_capacity(num_tokens, num_experts, 2 * capacity_factor,
                                min_capacity)

    indices1_s = jnp.argmax(gates, axis=1)
    mask1 = jax.nn.one_hot(indices1_s, num_experts, dtype=jnp.int32)

    assert rng is not None, "top2 gating needs rng (Gumbel 2nd-expert sampling)"
    rng, sub = jax.random.split(rng)
    logits_w_noise = logits + jax.random.gumbel(sub, logits.shape, jnp.float32)
    logits_except1 = jnp.where(mask1.astype(bool), -jnp.inf, logits_w_noise)
    indices2_s = jnp.argmax(logits_except1, axis=1)
    mask2 = jax.nn.one_hot(indices2_s, num_experts, dtype=jnp.int32)

    locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations2 = jnp.cumsum(mask2, axis=0) - 1
    locations2 = locations2 + jnp.sum(mask1, axis=0, keepdims=True)

    exp_counts = mask1.sum(axis=0)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(jnp.float32), axis=0)
    l_aux = jnp.mean(me * ce) * num_experts * num_experts

    mask1 = mask1 * (locations1 < capacity).astype(mask1.dtype)
    mask2 = mask2 * (locations2 < capacity).astype(mask2.dtype)

    locations1_s = jnp.sum(locations1 * mask1, axis=1)
    locations2_s = jnp.sum(locations2 * mask2, axis=1)

    mask1_f = mask1.astype(jnp.float32)
    mask2_f = mask2.astype(jnp.float32)
    gates1_s = jnp.einsum("se,se->s", gates, mask1_f)
    gates2_s = jnp.einsum("se,se->s", gates, mask2_f)
    denom_s = jnp.clip(gates1_s + gates2_s, min=jnp.finfo(jnp.float32).eps)
    gates1_s = gates1_s / denom_s
    gates2_s = gates2_s / denom_s
    # fold the drop mask back in: a capacity-dropped route must carry 0
    gates1_s = gates1_s * mask1_f.sum(axis=1)
    gates2_s = gates2_s * mask2_f.sum(axis=1)
    return (l_aux,
            [(indices1_s, locations1_s, gates1_s),
             (indices2_s, locations2_s, gates2_s)],
            exp_counts, capacity)


class TopKGate:
    """Gate module (reference ``TopKGate``, ``sharded_moe.py:353``).

    ``apply(params, x, rng)`` → ``(l_aux, combine_weights, dispatch_mask,
    exp_counts)``.  The linear gate projection runs in fp32 like the
    reference's ``self.wg`` float cast.
    """

    def __init__(self, model_dim: int, num_experts: int, k: int = 1,
                 capacity_factor: float = 1.0, eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4, noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, use_rts: bool = True,
                 max_capacity: Optional[int] = None):
        if k not in (1, 2):
            raise ValueError("Only top-1 and top-2 gatings are supported.")
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens
        self.use_rts = use_rts
        if max_capacity is not None and k != 1:
            raise ValueError(
                "max_capacity bounds the drop_tokens=False top-1 gate; "
                "top-2 gating always sizes capacity from capacity_factor "
                f"(got k={k})")
        self.max_capacity = max_capacity
        if not drop_tokens and k == 1 and max_capacity is None:
            from ..utils.logging import logger
            logger.warning(
                "drop_tokens=False defaults to the guaranteed no-drop "
                "capacity (= token count): nothing is ever dropped, at the "
                "cost of an S x E x S dispatch. Pass max_capacity=<bound> "
                "to cap the memory instead, monitoring drops via "
                "MoE.apply(..., return_overflow=True) / tokens_overflowed() "
                "or the engine's moe_tokens_dropped metric.")

    def init(self, rng):
        scale = 1.0 / math.sqrt(self.model_dim)
        w = jax.random.uniform(rng, (self.model_dim, self.num_experts),
                               jnp.float32, -scale, scale)
        return {"wg": w}

    def capacity_for(self, num_tokens: int, train: bool = True) -> int:
        """The static per-expert capacity ``apply`` will use for a batch of
        ``num_tokens`` — pair with ``tokens_overflowed(exp_counts, cap)`` to
        detect capacity drops (exact for top-1)."""
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 2:
            # top2gating sizes capacity at 2x the factor (two slots/token)
            return compute_capacity(num_tokens, self.num_experts, 2 * cf,
                                    self.min_capacity)
        if self.drop_tokens:
            return compute_capacity(num_tokens, self.num_experts, cf,
                                    self.min_capacity)
        return nodrop_capacity(num_tokens, self.num_experts,
                               self.max_capacity, self.min_capacity)

    def _logits(self, params, x, rng, train):
        x32 = x.reshape(-1, self.model_dim).astype(jnp.float32)
        logits = x32 @ params["wg"]

        noisy = self.noisy_gate_policy if train else None
        if noisy == "Jitter" and rng is not None:
            rng, sub = jax.random.split(rng)
            eps = 1e-2
            x32 = x32 * jax.random.uniform(sub, x32.shape, jnp.float32,
                                           1.0 - eps, 1.0 + eps)
            logits = x32 @ params["wg"]
        return logits, rng, noisy

    def apply(self, params, x, rng=None, used_token=None, train: bool = True):
        logits, rng, noisy = self._logits(params, x, rng, train)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(logits, cf, self.min_capacity, rng=rng,
                              used_token=used_token,
                              noisy_gate_policy=noisy,
                              drop_tokens=self.drop_tokens, use_rts=self.use_rts,
                              max_capacity=self.max_capacity)
        return top2gating(logits, cf, self.min_capacity, rng=rng)

    def apply_routes(self, params, x, rng=None, used_token=None,
                     train: bool = True):
        """Compact routing for the scatter dispatch: returns
        ``(l_aux, [(idx, loc, weight)] x k, exp_counts, capacity)``."""
        logits, rng, noisy = self._logits(params, x, rng, train)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            (l_aux, idx, loc, w, _kept, exp_counts, cap) = top1_routes(
                logits, cf, self.min_capacity, rng=rng,
                used_token=used_token, noisy_gate_policy=noisy,
                drop_tokens=self.drop_tokens, use_rts=self.use_rts,
                max_capacity=self.max_capacity)
            return l_aux, [(idx, loc, w)], exp_counts, cap
        return top2_routes(logits, cf, self.min_capacity, rng=rng)
