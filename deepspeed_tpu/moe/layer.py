"""MoE layer: gate + dispatch + experts + combine.

Parity: reference ``deepspeed/moe/layer.py:18`` (``MoE``) and
``sharded_moe.py:443`` (``MOELayer``).  TPU re-design:

- The reference's ``_AllToAll`` autograd op over an expert-parallel NCCL
  group (``sharded_moe.py:85``, applied :525,:542) becomes a *sharding
  constraint*: the dispatched ``(E, C, M)`` tensor is constrained to
  ``P('expert', ...)`` while tokens are sharded over the batch axes, and
  XLA's SPMD partitioner inserts the all-to-all pair on the ``expert`` mesh
  axis (differentiable for free — no custom autograd Function).
- Expert-parallel process groups (``utils/groups.py:107
  _create_expert_and_data_parallel``) are replaced by the ``expert`` mesh
  axis; "EP as a sub-grouping of DP ranks" is expressed by including
  ``expert`` in the batch sharding axes (see ``parallel/mesh.py``).
- PR-MoE residual path (``layer.py:154-161``): softmax-weighted sum of the
  expert output and a dense residual MLP via a learned 2-way coefficient.

``MoE.apply`` returns ``(output, l_aux, exp_counts)`` exactly like the
reference's ``MoE.forward`` (``return_overflow=True`` appends the
capacity-drop count); the internal ``MOELayer.apply`` always returns the
4-tuple.
"""

import itertools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .experts import Experts
from .sharded_moe import TopKGate, tokens_overflowed
from ..parallel.mesh import maybe_constrain
from ..utils.logging import log_dist


class MOELayer:
    """GShard Algorithm 2 over the ``expert`` mesh axis.

    ``dispatch_impl``:

    - ``"scatter"`` (default): tokens scatter into the (E, C, M) buffer by
      their (expert, slot) address and gather back weighted — O(S·M) data
      movement.  TPU-native replacement for the reference's ``_AllToAll``
      dispatch (``sharded_moe.py:85,525``): the sharding constraint on the
      scattered buffer makes XLA emit the all-to-all.
    - ``"einsum"``: the GShard one-hot formulation — an S×(E·C) matmul each
      way, O(S²·M·cf) FLOPs.  MXU-friendly but quadratic in tokens; kept as
      the numerics oracle and for comparison (examples/bench_moe.py).
    """

    # per-instance wire site ids: distinct layers (even same-shaped ones
    # in one model) must each contribute their own exchange to the wire's
    # census expectation, while retraces of the SAME layer dedup
    # (moe_wire.MoEWire._record)
    _wire_sites = itertools.count()

    def __init__(self, gate: TopKGate, experts: Experts,
                 dispatch_impl: str = "scatter"):
        assert dispatch_impl in ("scatter", "einsum"), dispatch_impl
        self.gate = gate
        self.experts = experts
        self.dispatch_impl = dispatch_impl
        self._wire_site = next(MOELayer._wire_sites)

    def init(self, rng):
        g, e = jax.random.split(rng)
        return {"gate": self.gate.init(g), "experts": self.experts.init(e)}

    def _active_wire(self, E: int, C: int, d_model: int):
        """The engine-installed quantized expert wire, iff it applies
        here: scatter dispatch only, shape supported, and the trace is
        actually running under the wire's mesh (a leaked wire from
        another engine's mesh must fall back, never mis-shard)."""
        from ..runtime.comm import moe_wire as mw
        wire = mw.get_active()
        if wire is None or not wire.supports(E, C, d_model):
            return None
        am = jax.sharding.get_abstract_mesh()
        if am.empty or dict(am.shape) != dict(wire.mesh.shape):
            return None
        return wire

    def apply(self, params, x, rng=None, used_token=None, train: bool = True):
        d_model = x.shape[-1]
        reshaped = x.reshape(-1, d_model)

        if rng is not None:
            gate_rng, expert_rng = jax.random.split(rng)
        else:
            gate_rng = expert_rng = None

        wire = None
        if self.dispatch_impl == "scatter":
            l_aux, routes, exp_counts, C = self.gate.apply_routes(
                params["gate"], reshaped, rng=gate_rng,
                used_token=used_token, train=train)
            E = self.gate.num_experts
            # dispatch: scatter each kept token to its (expert, slot) row;
            # dropped routes (weight 0) address the OOB row and vanish
            positions = []
            for idx, loc, w in routes:
                pos = jnp.where(w > 0, idx * C + loc, E * C)
                positions.append((pos, w))
            wire = self._active_wire(E, C, d_model)
            if wire is not None:
                # quantized expert exchange (runtime/comm/moe_wire.py):
                # int8 + per-block scales on every all_to_all hop, the
                # gate/capacity math above untouched
                pos_stack = jnp.stack([pos for pos, _ in positions])
                dispatched = wire.dispatch(reshaped, pos_stack, E, C,
                                           site=self._wire_site)
            else:
                flat = jnp.zeros((E * C, d_model), x.dtype)
                for pos, _ in positions:
                    flat = flat.at[pos].set(reshaped, mode="drop")
                dispatched = flat.reshape(E, C, d_model)
        else:
            l_aux, combine_weights, dispatch_mask, exp_counts = \
                self.gate.apply(params["gate"], reshaped, rng=gate_rng,
                                used_token=used_token, train=train)
            C = dispatch_mask.shape[2]
            # dispatch: (S,E,C) × (S,M) → (E,C,M)
            dispatched = jnp.einsum("sec,sm->ecm",
                                    dispatch_mask.astype(x.dtype), reshaped)

        # constraining the expert axis makes XLA emit the forward
        # all-to-all (reference :525); the quantized wire already landed
        # the buffer expert-sharded
        dispatched = maybe_constrain(dispatched, P("expert", None, None))
        expert_output = self.experts.apply(params["experts"], dispatched,
                                           rng=expert_rng)
        expert_output = maybe_constrain(expert_output, P("expert", None, None))

        if self.dispatch_impl == "scatter":
            if wire is not None:
                rows = wire.combine(expert_output, pos_stack,
                                    site=self._wire_site)       # (k, S, M)
                combined = 0.0
                for r, (_, w) in enumerate(positions):
                    combined = combined + rows[r] * \
                        w[:, None].astype(x.dtype)
            else:
                flat_out = expert_output.reshape(-1, d_model)
                combined = 0.0
                for pos, w in positions:
                    row = flat_out[jnp.clip(pos, 0, flat_out.shape[0] - 1)]
                    combined = combined + row * w[:, None].astype(x.dtype)
        else:
            # combine: (S,E,C) × (E,C,M) → (S,M); the contraction back to
            # token-sharded output is the reverse all-to-all (reference :542)
            combined = jnp.einsum("sec,ecm->sm",
                                  combine_weights.astype(x.dtype),
                                  expert_output)
        # capacity drops are detectable: exp_counts is pre-thinning demand
        overflow = tokens_overflowed(exp_counts, C)
        return combined.reshape(x.shape), l_aux, exp_counts, overflow

    def partition_specs(self, params):
        return {"gate": jax.tree_util.tree_map(lambda p: P(), params["gate"]),
                "experts": self.experts.partition_specs(params["experts"])}


class MoE:
    """User-facing MoE layer (reference ``deepspeed/moe/layer.py:18``).

    ``expert`` follows the layer protocol (``.init``/``.apply``) and must map
    ``(..., hidden_size) → (..., hidden_size)``.
    """

    def __init__(self, hidden_size: int, expert, num_experts: int = 1,
                 ep_size: int = 1, k: int = 1, capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0, min_capacity: int = 4,
                 use_residual: bool = False,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, use_rts: bool = True,
                 max_capacity: Optional[int] = None,
                 dispatch_impl: str = "scatter"):
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        # ep_size is advisory here: actual expert parallelism is the mesh's
        # ``expert`` axis extent; kept for config/API parity (the reference
        # builds NCCL groups from it, ``layer.py:113``)
        self.ep_size = min(ep_size, num_experts)
        self.use_residual = use_residual
        assert noisy_gate_policy is None or noisy_gate_policy in \
            ("None", "Jitter", "RSample"), \
            "Unsupported noisy_gate_policy: " + str(noisy_gate_policy)

        log_dist(f"Creating MoE layer with num_experts: {num_experts} | "
                 f"expert_parallel_size (advisory): {self.ep_size}", ranks=[0])

        self.expert = expert
        self.moe_layer = MOELayer(
            TopKGate(hidden_size, num_experts, k, capacity_factor,
                     eval_capacity_factor, min_capacity, noisy_gate_policy,
                     drop_tokens, use_rts, max_capacity=max_capacity),
            Experts(expert, num_experts), dispatch_impl=dispatch_impl)

    def init(self, rng):
        r_moe, r_mlp, r_coef = jax.random.split(rng, 3)
        params = {"moe": self.moe_layer.init(r_moe)}
        if self.use_residual:
            params["mlp"] = self.expert.init(r_mlp)
            scale = 0.02
            params["coefficient"] = {
                "w": jax.random.normal(r_coef, (self.hidden_size, 2),
                                       jnp.float32) * scale,
                "b": jnp.zeros((2,), jnp.float32)}
        return params

    def apply(self, params, x, rng=None, used_token=None, train: bool = True,
              return_overflow: bool = False):
        """Returns ``(output, l_aux, exp_counts)`` (reference ``MoE.forward``).

        ``return_overflow=True`` appends the number of tokens dropped by
        capacity thinning this call (exact for top-1) — the runtime signal
        for a too-small ``max_capacity`` / skewed routing under
        ``drop_tokens=False``."""
        output, l_aux, exp_counts, overflow = self.moe_layer.apply(
            params["moe"], x, rng=rng, used_token=used_token, train=train)
        if self.use_residual:
            out_mlp = self.expert.apply(params["mlp"], x, rng=rng)
            if isinstance(out_mlp, tuple):
                out_mlp = out_mlp[0]
            coef = (x @ params["coefficient"]["w"].astype(x.dtype)
                    + params["coefficient"]["b"].astype(x.dtype))
            coef = jax.nn.softmax(coef, axis=-1)
            output = output * coef[..., 0:1] + out_mlp * coef[..., 1:]
        if return_overflow:
            return output, l_aux, exp_counts, overflow
        return output, l_aux, exp_counts

    def partition_specs(self, params):
        specs = {"moe": self.moe_layer.partition_specs(params["moe"])}
        if self.use_residual:
            specs["mlp"] = jax.tree_util.tree_map(lambda p: P(), params["mlp"])
            specs["coefficient"] = jax.tree_util.tree_map(
                lambda p: P(), params["coefficient"])
        return specs
