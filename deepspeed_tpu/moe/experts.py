"""Stacked expert bundle.

Parity: reference ``deepspeed/moe/experts.py:9`` (``Experts``) — a ModuleList
of deep-copied expert modules, each applied to its chunk of the dispatched
tensor.  TPU re-design: ONE stacked parameter pytree with a leading expert
axis, applied with ``jax.vmap`` — a single batched einsum per weight instead
of a Python loop of per-expert matmuls, so the MXU sees one large batched
contraction and the expert axis can be sharded over the ``expert`` mesh axis.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class Experts:
    """``num_experts`` copies of ``expert`` with stacked parameters.

    ``expert`` follows the layer protocol (``.init(rng)``, ``.apply``).
    ``init`` → pytree whose leaves have a leading ``(num_experts,)`` axis.
    ``apply(params, x)`` with ``x: (E, C, M)`` → ``(E, C, M_out)``.
    """

    def __init__(self, expert, num_experts: int = 1):
        self.expert = expert
        self.num_experts = num_experts

    def init(self, rng):
        rngs = jax.random.split(rng, self.num_experts)
        return jax.vmap(self.expert.init)(rngs)

    def apply(self, params, x, rng=None):
        def one(p, xe, r):
            out = self.expert.apply(p, xe, rng=r)
            if isinstance(out, tuple):
                out = out[0]
            return out
        if rng is not None:
            rngs = jax.random.split(rng, self.num_experts)
            return jax.vmap(one)(params, x, rngs)
        return jax.vmap(lambda p, xe: one(p, xe, None))(params, x)

    def partition_specs(self, params):
        """Expert axis sharded over the ``expert`` mesh axis; inner expert
        weight axes left for fsdp/tensor composition (reference: expert params
        are per-EP-rank, ``experts.py:20 param.allreduce=False``)."""
        return jax.tree_util.tree_map(
            lambda p: P(*(("expert",) + (None,) * (p.ndim - 1))), params)
