"""Mixture-of-Experts / expert parallelism.

Parity: reference ``deepspeed/moe/`` — ``MoE`` (``layer.py:18``), gating
(``sharded_moe.py``), ``Experts`` (``experts.py:9``).  Expert parallelism
rides the ``expert`` mesh axis (see ``parallel/mesh.py``).
"""

from .layer import MoE, MOELayer
from .experts import Experts
from .sharded_moe import (TopKGate, top1gating, top2gating, top1_routes,
                          top2_routes, compute_capacity, nodrop_capacity,
                          tokens_overflowed)
from .utils import is_moe_param_path, split_moe_params

__all__ = ["MoE", "MOELayer", "Experts", "TopKGate", "top1gating",
           "top2gating", "top1_routes", "top2_routes", "compute_capacity",
           "nodrop_capacity", "tokens_overflowed", "is_moe_param_path",
           "split_moe_params"]
